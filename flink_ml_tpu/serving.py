"""Micro-batch serving — double-buffered, overload-graceful fused inference.

The throughput path the ROADMAP north star asks for: drive a fused
`PipelineModel` transform plan (pipeline.py) over an unbounded stream of
mini-batches at a bounded, stage-count-independent host-sync cost — and
keep that true when the offered load exceeds capacity or a dependency
flakes. Mechanisms on top of the fusion planner:

1. **Bucket padding** — a jitted segment program is specialized to its
   input shapes, so free-running batch sizes would recompile every batch.
   Each incoming batch is padded up to the smallest configured bucket
   (default: powers of two) by REPEATING ITS LAST ROW; compile count is
   bounded by the number of buckets, and the padding rows are copies of a
   real row, so they can never fire a validation guard the real data
   would not. Outputs are sliced back to the true row count on device.

2. **Bounded in-flight window** — the transform of batch i is dispatched
   with its exit guard drain DEFERRED (PipelineModel.transform_deferred),
   and the (output, pending-guards) pair parks in a `flow.BoundedChannel`
   of capacity `in_flight`. Batch i+1's H2D upload and segment dispatch
   overlap batch i's device compute; the single blocking guard readback
   happens only when a batch leaves the window. Per-batch host syncs are
   therefore O(1) regardless of pipeline depth.

3. **Admission control + deadlines** (`submit`/`results`, the push API) —
   an admission `BoundedChannel` with the `reject` policy in front of the
   dispatch loop: once `admission` requests wait, `submit` fast-fails
   with a typed `ServerOverloaded` carrying the live queue depth, so an
   overloaded server sheds load at the door with bounded memory and
   bounded client latency instead of growing a queue until the host
   dies. A request may carry a deadline: expired-before-dispatch requests
   are shed without paying compute (`serving.deadlineMiss` +
   status `"expired"`), finished-after-deadline results deliver marked
   `"late"`.

4. **Transient-fault resilience** — batch dispatch runs under
   `flow.with_retries` (`config.transient_retries`, the
   `serving.batch` fault site), so a transiently-failing backend retries
   with backoff instead of killing the stream; non-transient errors
   surface per-request (`status "error"`), never silently dropped. A
   `flow.StragglerWatchdog` times every dispatch and flags executions
   beyond `config.straggler_factor`× the trailing mean. `health()`
   returns a `ServerHealth` snapshot of all of it.

5. **Model hot-swap hooks** — a `lifecycle.ModelLifecycle` attached via
   the `lifecycle` param receives every retired batch's guard outcome:
   swap-capable stages in the served plan (online models) take their
   model tensors as versioned runtime operands, so a trainer promoting
   versions mid-serve never pauses this server, and a run of guard
   errors rolls traffic back to the last-good version automatically
   (docs/model_lifecycle.md).

Results are yielded IN ORDER. A batch's guard failure (e.g. Bucketizer
handleInvalid='error') raises when that batch is yielded — at most
`in_flight` batches later than the eager path would have raised, never
reordered and never dropped. When the consumer abandons `serve` early (a
`close()`/GeneratorExit) or a deferred guard error terminates it, the
still-in-flight window is drained and released — no staged device buffers
or queue slots leak (`serving.cancelled` counts the released batches).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import config, flow
from .ckpt import faults
from .obs import hist, memledger, timeline, tracing
from .parallel.prefetch import next_bucket, pad_rows, slice_rows, stage_to_device
from .pipeline import PipelineModel, _drain_guards
from .table import SparseBatch, Table
from .utils import metrics

__all__ = [
    "MicroBatchServer",
    "ServerHealth",
    "ServerOverloaded",
    "ServeResult",
    "serve_stream",
]

# The bucket schedule and repeat-last-row pad live in
# parallel/prefetch.py, shared with the stream-training staging paths —
# same policy, same guard-safety argument, one implementation.
_next_bucket, _pad_rows, _slice_rows = next_bucket, pad_rows, slice_rows


class ServerOverloaded(flow.ChannelRejected):
    """`submit` fast-fail: the admission queue is full. Carries the live
    queue depth and capacity (inherited from `flow.ChannelRejected`) so a
    client can back off / divert instead of parsing a message."""


@dataclass
class ServeResult:
    """One retired request from the push API, in submission order.
    `status` is `"ok"`, `"late"` (finished past its deadline), `"expired"`
    (deadline passed before dispatch — no compute paid, `table` is None)
    or `"error"` (`error` holds the exception; the stream continues)."""

    seq: int
    status: str
    table: Optional[Table] = None
    error: Optional[BaseException] = None


@dataclass
class ServerHealth:
    """Point-in-time server snapshot — the serving analogue of
    `DeviceEpochCache.stats`: every overload decision the server made,
    queriable without scraping the metrics registry."""

    inFlight: int  # window capacity
    windowDepth: int  # transformed-but-undrained batches right now
    admissionCapacity: int
    admissionDepth: int  # submitted-but-undispatched requests right now
    submitted: int  # requests accepted by submit()
    rejected: int  # submits refused at the door (ServerOverloaded)
    completed: int  # results delivered (any status)
    expired: int  # shed before dispatch: deadline already passed
    late: int  # delivered after their deadline
    errors: int  # per-request failures delivered as status "error"
    retries: int  # transient-fault retries paid by batch dispatch
    cancelled: int  # in-flight batches released by an early serve() exit
    bucketsSeen: int
    emaBatchMs: float  # dispatch trailing-mean latency (watchdog EMA)
    stragglers: int  # dispatches flagged beyond straggler_factor x mean
    # HBM ledger view (obs/memledger.py): total ledgered device-resident
    # bytes and the global peak watermark at snapshot time — memory sits
    # on the SLO surface next to the stage latencies, because the paging
    # work (ROADMAP item 3) is graded against exactly these numbers
    hbmLiveBytes: int = 0
    hbmPeakBytes: int = 0
    # per-stage latency percentiles from obs/hist.py (p50/p90/p99/p999 +
    # count per stage: queueWait, batchForm, dispatch, readback,
    # deadlineMargin) — the SLO surface; empty until samples exist or
    # when histograms are disabled
    stageLatencyMs: Dict[str, Dict[str, float]] = None

    #: The serving stage-attribution histograms (obs/hist.py names, all
    #: in milliseconds): queue-wait (submit -> dispatch start), batch
    #: formation (pad + H2D upload), dispatch (fused-plan launch),
    #: readback (the one blocking guard drain), and the remaining
    #: deadline margin at delivery (clamped at 0; lateness lands in
    #: `serving.lateByMs` and the deadlineMiss.late counter).
    STAGES = (
        ("queueWait", "serving.queueWaitMs"),
        ("batchForm", "serving.batchFormMs"),
        ("dispatch", "serving.dispatchMs"),
        ("readback", "serving.readbackMs"),
        ("deadlineMargin", "serving.deadlineMarginMs"),
    )


class MicroBatchServer:
    """Drives a PipelineModel's fused transform plan over a batch stream.

    `in_flight` bounds the transformed-but-undrained window (default
    `config.serving_in_flight`); `buckets` optionally pins the padded
    batch-shape schedule (sorted ascending), otherwise batches pad to the
    next power of two. `device_input=True` uploads each padded batch's
    numeric host columns to device HBM before dispatch, so the whole
    pipeline — upload included — runs ahead of the previous batch's drain.
    `admission` bounds the push API's submit queue (default
    `config.serving_admission`); `deadline_ms` is the default per-request
    deadline (None = none); `retries` the transient-fault retry budget for
    batch dispatch (default `config.transient_retries`).

    Two consumption styles:

    - `serve(stream)` — the pull loop: the caller owns pacing, the window
      gives lossless credit-based backpressure (the `block` policy).
    - `submit(batch)` + `results()` — the push loop: a dispatch worker
      consumes an admission queue with the `reject` policy; `submit`
      raises `ServerOverloaded` once `admission` requests wait.
    """

    def __init__(
        self,
        model: PipelineModel,
        in_flight: Optional[int] = None,
        buckets: Optional[Sequence[int]] = None,
        device_input: bool = True,
        admission: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        retries: Optional[int] = None,
        lifecycle=None,
    ):
        if not isinstance(model, PipelineModel):
            raise TypeError(f"MicroBatchServer serves a PipelineModel, got {type(model).__name__}")
        self.model = model
        self.in_flight = max(1, int(in_flight if in_flight is not None else config.serving_in_flight))
        self.buckets = sorted(int(b) for b in buckets) if buckets else None
        self.device_input = device_input
        self.admission = max(
            1, int(admission if admission is not None else config.serving_admission)
        )
        self.deadline_ms = deadline_ms if deadline_ms is not None else config.serving_deadline_ms
        self.retries = retries
        # optional lifecycle.ModelLifecycle: every retired batch's guard
        # outcome feeds its sliding health window, so a run of guard
        # errors (a bad promotion that slipped the gate) triggers the
        # automatic rollback WITHOUT restarting this server — the swap is
        # a pointer exchange the next batch picks up
        self.lifecycle = lifecycle
        self.watchdog = flow.StragglerWatchdog("serving.batch")
        self._buckets_seen: set = set()
        self._counts: Dict[str, int] = {
            "completed": 0,
            "expired": 0,
            "late": 0,
            "errors": 0,
            "retries": 0,
            "cancelled": 0,
        }
        self._window: Optional[flow.BoundedChannel] = None  # latest serve window
        self._requests: Optional[flow.BoundedChannel] = None
        self._out: Optional[flow.BoundedChannel] = None
        self._worker = None
        self._seq = 0

    # -- batch staging -------------------------------------------------------
    def _stage_batch(self, batch: Table) -> Tuple[Table, int]:
        """Pad `batch` to its bucket and (optionally) upload numeric host
        columns — the H2D leg of the double buffer. All uploadable columns
        go through ONE `device_put` call (per-column puts would each pay a
        dispatch; on a remote-attached device, a round trip)."""
        n = batch.num_rows
        bucket = _next_bucket(n, self.buckets)
        self._buckets_seen.add(bucket)
        cols: Dict[str, Any] = {}
        uploads: Dict[str, Any] = {}
        for name in batch.column_names:
            col = _pad_rows(batch.column(name), n, bucket)
            if self.device_input and self._uploadable(col):
                uploads[name] = col
            else:
                cols[name] = col
        if uploads:
            from .table import register_device_pytrees

            register_device_pytrees()  # SparseBatch uploads as a pytree
            # accounted (h2d.bytes/count) + ledgered: the in-flight window
            # holds these buffers until the batch retires, so `serving`
            # residency tracks the window depth live
            uploads = stage_to_device(uploads, category="serving")
        return Table(
            {name: uploads.get(name, cols.get(name)) for name in batch.column_names}
        ), n

    @staticmethod
    def _uploadable(col) -> bool:
        if isinstance(col, SparseBatch):
            return isinstance(col.indices, np.ndarray)
        return (
            isinstance(col, np.ndarray)
            and col.dtype != object
            and col.dtype.kind not in ("U", "S")
        )

    def _dispatch(self, batch: Table, index: int):
        """Stage + dispatch one batch under the transient-retry budget
        and the straggler watchdog. The `serving.batch` fault site sits
        inside the retried unit, so a `faults.flaky` plan exercises the
        retry path end to end; staging re-runs with the dispatch (an
        upload that failed mid-flight cannot be trusted half-done)."""

        def attempt():
            faults.tick("serving.batch")
            t0 = time.perf_counter()
            staged, n = self._stage_batch(batch)
            t1 = time.perf_counter()
            out, pending = self.model.transform_deferred(staged)
            t2 = time.perf_counter()
            # stage attribution (obs/hist.py): where a request's latency
            # sits BEFORE the blocking drain — the serving mirror of the
            # training loop's dispatch-wall split
            hist.record("serving.batchFormMs", (t1 - t0) * 1000.0)
            hist.record("serving.dispatchMs", (t2 - t1) * 1000.0)
            if timeline.enabled():
                timeline.record_complete(
                    timeline.LANE_SERVING,
                    "serving.batchForm",
                    int(t0 * 1e9),
                    int((t1 - t0) * 1e9),
                    index=index,
                )
                timeline.record_complete(
                    timeline.LANE_SERVING,
                    "serving.dispatch",
                    int(t1 * 1e9),
                    int((t2 - t1) * 1e9),
                    index=index,
                )
            return out, pending, n

        with tracing.span("serving.batch", index=index, op="dispatch"):
            with self.watchdog.observe():
                return flow.with_retries(
                    attempt,
                    site="serving.batch",
                    retries=self.retries,
                    on_retry=lambda e, a: self._count("retries"),
                )

    def _count(self, key: str, n: int = 1) -> None:
        self._counts[key] = self._counts.get(key, 0) + n

    def _finish(self, out: Table, pending: List[Tuple[str, Any]], n: int) -> Table:
        """Retire one batch from the in-flight window: ONE packed guard
        readback (the batch's only blocking sync), then slice the padding
        off on device. The guard outcome feeds the attached lifecycle's
        health window (rollback trigger)."""
        t0 = time.perf_counter()
        try:
            _drain_guards(pending)
        except Exception as e:
            if self.lifecycle is not None:
                self.lifecycle.record_guard_error(e)
            raise
        finally:
            dt = time.perf_counter() - t0
            hist.record("serving.readbackMs", dt * 1000.0)
            if timeline.enabled():
                timeline.record_complete(
                    timeline.LANE_SERVING,
                    "serving.readback",
                    int(t0 * 1e9),
                    int(dt * 1e9),
                )
        if self.lifecycle is not None:
            self.lifecycle.record_serve_ok()
        if out.num_rows == n:
            return out
        return Table({name: _slice_rows(out.column(name), n) for name in out.column_names})

    def _release(self, window: flow.BoundedChannel) -> None:
        """Early-exit cleanup: drop every still-in-flight batch — staged
        device buffers and pending guard handles release with their
        references, and the window's queue slots free — so an abandoned
        serve() (consumer close, deferred-guard error) leaks nothing.
        The abandoned guards are never drained: raising NEW errors out of
        a generator teardown would mask the one the consumer saw."""
        leaked = window.cancel()
        if leaked:
            metrics.inc_counter("serving.cancelled", len(leaked))
            self._count("cancelled", len(leaked))
        metrics.set_gauge("serving.buckets", len(self._buckets_seen))

    # -- the pull serving loop ----------------------------------------------
    def serve(self, stream: Iterable[Table]) -> Iterator[Table]:
        """Transform every batch of `stream`, yielding output Tables in
        input order. Output columns may be device-resident; callers that
        need host values materialize them (that readback is theirs)."""
        window = flow.BoundedChannel(self.in_flight, policy=flow.BLOCK, name="serving.window")
        self._window = window
        num_batches = 0
        metrics.set_gauge("serving.in_flight", self.in_flight)
        try:
            for batch in stream:
                entry = self._dispatch(batch, num_batches)
                if not window.offer(entry):  # window full: retire the oldest
                    # tpulint: disable=untimed-wait -- single-threaded pull loop: offer() just returned False, so the window is non-empty and get() cannot block
                    yield self._finish(*window.get())
                    window.offer(entry)
                num_batches += 1
                metrics.inc_counter("serving.batches")
                metrics.inc_counter("serving.records", entry[2])
                metrics.set_gauge("serving.buckets", len(self._buckets_seen))
            while len(window):
                # tpulint: disable=untimed-wait -- single-threaded pull loop: guarded by len(window) > 0, get() cannot block
                yield self._finish(*window.get())
        finally:
            self._release(window)

    # -- the push serving loop: admission control + deadlines ----------------
    def start(self) -> None:
        """Bring up the dispatch worker and its channels (idempotent;
        `submit` auto-starts)."""
        if self._worker is not None:
            return
        self._requests = flow.BoundedChannel(
            self.admission, policy=flow.REJECT, name="serving.admit"
        )
        # results buffer: sized so a retired batch never blocks the worker
        # while the admission queue and window both stay full — the
        # consumer's pull pace backpressures through it
        self._out = flow.BoundedChannel(
            self.admission + self.in_flight + 1, policy=flow.BLOCK, name="serving.results"
        )
        metrics.set_gauge("serving.in_flight", self.in_flight)
        self._worker = flow.spawn(self._run, name="serving.dispatch")

    def submit(self, batch: Table, deadline_ms: Optional[float] = None) -> int:
        """Admit one batch, returning its sequence number. Raises
        `ServerOverloaded` (with the live queue depth) when `admission`
        requests already wait — the typed fast-fail of the `reject`
        policy. `deadline_ms` overrides the server default."""
        if self._worker is None:
            self.start()
        ms = deadline_ms if deadline_ms is not None else self.deadline_ms
        deadline = None if ms is None else time.monotonic() + ms / 1000.0
        seq = self._seq
        try:
            self._requests.put((seq, batch, deadline, time.monotonic()))
        except flow.ChannelRejected as e:
            metrics.inc_counter("serving.rejected")
            raise ServerOverloaded(e.channel, e.depth, e.capacity) from None
        self._seq += 1
        metrics.inc_counter("serving.batches")
        metrics.inc_counter("serving.records", batch.num_rows)
        return seq

    def close(self) -> None:
        """No more submits; the worker drains what was admitted and closes
        the results stream."""
        if self._requests is not None:
            self._requests.close()

    def results(self) -> Iterator[ServeResult]:
        """Retired requests in submission order (`ServeResult`); ends when
        `close()` has been called and every admitted request retired."""
        if self._worker is None:
            self.start()
        yield from self._out

    def health(self) -> ServerHealth:
        """A `ServerHealth` snapshot of queues, overload decisions, retry
        spend, dispatch latency, and the per-stage latency percentiles
        (`stageLatencyMs`, from the obs/hist.py histograms)."""
        stage_latency: Dict[str, Dict[str, float]] = {}
        for label, hist_name in ServerHealth.STAGES:
            p = hist.percentiles(hist_name)
            if p is not None:
                stage_latency[label] = {
                    k: p[k] for k in ("count", "p50", "p90", "p99", "p999")
                }
        window_depth = len(self._window) if self._window is not None else 0
        adm_depth = len(self._requests) if self._requests is not None else 0
        rejected = (
            self._requests.stats.rejected if self._requests is not None else 0
        )
        submitted = self._requests.stats.puts if self._requests is not None else 0
        return ServerHealth(
            inFlight=self.in_flight,
            windowDepth=window_depth,
            admissionCapacity=self.admission,
            admissionDepth=adm_depth,
            submitted=submitted,
            rejected=rejected,
            completed=self._counts["completed"],
            expired=self._counts["expired"],
            late=self._counts["late"],
            errors=self._counts["errors"],
            retries=self._counts["retries"],
            cancelled=self._counts["cancelled"],
            bucketsSeen=len(self._buckets_seen),
            emaBatchMs=self.watchdog.trailing_mean_s * 1000.0,
            stragglers=metrics.get_counter("flow.straggler.serving.batch", 0),
            hbmLiveBytes=memledger.live_bytes(),
            hbmPeakBytes=memledger.peak_bytes(),
            stageLatencyMs=stage_latency,
        )

    def _run(self) -> None:
        """Dispatch worker: admission queue → window → results, deadlines
        enforced at both ends. Any worker-level failure closes the results
        channel with the error — consumers re-raise instead of hanging."""
        window = flow.BoundedChannel(self.in_flight, policy=flow.BLOCK, name="serving.window")
        self._window = window
        try:
            for seq, batch, deadline, submitted in self._requests:
                hist.record(
                    "serving.queueWaitMs", (time.monotonic() - submitted) * 1000.0
                )
                if deadline is not None and time.monotonic() > deadline:
                    # shed BEFORE paying staging/compute: the client
                    # already gave up on this request. Cause-attributed:
                    # expired-IN-QUEUE (vs late-after-dispatch below) —
                    # `serving.deadlineMiss` stays the compatibility sum
                    metrics.inc_counter("serving.deadlineMiss")
                    metrics.inc_counter("serving.deadlineMiss.expired")
                    self._count("expired")
                    self._emit(ServeResult(seq, "expired"))
                    continue
                try:
                    entry = self._dispatch(batch, seq)
                except Exception as e:  # per-request failure: stream survives
                    self._count("errors")
                    self._emit(ServeResult(seq, "error", error=e))
                    continue
                if not window.offer((seq, deadline) + entry):
                    # tpulint: disable=untimed-wait -- dispatch-worker-local window: offer() just returned False, so the window is non-empty and get() cannot block
                    self._retire(window.get())
                    window.offer((seq, deadline) + entry)
            while len(window):
                # tpulint: disable=untimed-wait -- dispatch-worker-local window: guarded by len(window) > 0, get() cannot block
                self._retire(window.get())
            self._out.close()
        except BaseException as e:  # worker death must not strand consumers
            self._out.close(error=e)
        finally:
            self._release(window)

    def _retire(self, entry) -> None:
        seq, deadline, out, pending, n = entry
        try:
            table = self._finish(out, pending, n)
        except Exception as e:  # deferred guard error: per-request, in order
            self._count("errors")
            self._emit(ServeResult(seq, "error", error=e))
            return
        status = "ok"
        if deadline is not None:
            margin_ms = (deadline - time.monotonic()) * 1000.0
            if margin_ms < 0:
                # cause-attributed miss: finished LATE after dispatch (the
                # compute was paid — contrast deadlineMiss.expired)
                metrics.inc_counter("serving.deadlineMiss")
                metrics.inc_counter("serving.deadlineMiss.late")
                hist.record("serving.lateByMs", -margin_ms)
                self._count("late")
                status = "late"
            else:
                hist.record("serving.deadlineMarginMs", margin_ms)
        self._emit(ServeResult(seq, status, table=table))

    def _emit(self, result: ServeResult) -> None:
        self._count("completed")
        try:
            self._out.put(result)
        except flow.ChannelClosed:  # consumer cancelled results(): drop
            pass


def serve_stream(
    model: PipelineModel,
    stream: Iterable[Table],
    in_flight: Optional[int] = None,
    buckets: Optional[Sequence[int]] = None,
) -> List[Table]:
    """One-shot convenience: serve the whole stream, collect the outputs."""
    return list(MicroBatchServer(model, in_flight=in_flight, buckets=buckets).serve(stream))
