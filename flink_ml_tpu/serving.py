"""Micro-batch serving — double-buffered, overload-graceful fused inference.

The throughput path the ROADMAP north star asks for: drive a fused
`PipelineModel` transform plan (pipeline.py) over an unbounded stream of
mini-batches at a bounded, stage-count-independent host-sync cost — and
keep that true when the offered load exceeds capacity or a dependency
flakes. Mechanisms on top of the fusion planner:

1. **Bucket padding** — a jitted segment program is specialized to its
   input shapes, so free-running batch sizes would recompile every batch.
   Each incoming batch is padded up to the smallest configured bucket
   (default: powers of two) by REPEATING ITS LAST ROW; compile count is
   bounded by the number of buckets, and the padding rows are copies of a
   real row, so they can never fire a validation guard the real data
   would not. Outputs are sliced back to the true row count on device.

2. **Bounded in-flight window** — the transform of batch i is dispatched
   with its exit guard drain DEFERRED (PipelineModel.transform_deferred),
   and the (output, pending-guards) pair parks in a `flow.BoundedChannel`
   of capacity `in_flight`. Batch i+1's H2D upload and segment dispatch
   overlap batch i's device compute; the single blocking guard readback
   happens only when a batch leaves the window. Per-batch host syncs are
   therefore O(1) regardless of pipeline depth.

3. **Admission control + deadlines** (`submit`/`results`, the push API) —
   an admission `BoundedChannel` with the `reject` policy in front of the
   dispatch loop: once `admission` requests wait, `submit` fast-fails
   with a typed `ServerOverloaded` carrying the live queue depth, so an
   overloaded server sheds load at the door with bounded memory and
   bounded client latency instead of growing a queue until the host
   dies. A request may carry a deadline: expired-before-dispatch requests
   are shed without paying compute (`serving.deadlineMiss` +
   status `"expired"`), finished-after-deadline results deliver marked
   `"late"`.

4. **Transient-fault resilience** — batch dispatch runs under
   `flow.with_retries` (`config.transient_retries`, the
   `serving.batch` fault site), so a transiently-failing backend retries
   with backoff instead of killing the stream; non-transient errors
   surface per-request (`status "error"`), never silently dropped. A
   `flow.StragglerWatchdog` times every dispatch and flags executions
   beyond `config.straggler_factor`× the trailing mean. `health()`
   returns a `ServerHealth` snapshot of all of it.

5. **Model hot-swap hooks** — a `lifecycle.ModelLifecycle` attached via
   the `lifecycle` param receives every retired batch's guard outcome:
   swap-capable stages in the served plan (online models) take their
   model tensors as versioned runtime operands, so a trainer promoting
   versions mid-serve never pauses this server, and a run of guard
   errors rolls traffic back to the last-good version automatically
   (docs/model_lifecycle.md).

6. **Continuous batching + the multi-tenant model store** — with
   `batching="continuous"` the dispatch worker admits requests into the
   FORMING batch mid-flight instead of dispatching each submit alone: a
   forming batch goes out the moment it fills its target bucket
   (`form_rows`) OR its oldest request's deadline margin hits the
   forming budget (`config.serving_form_budget_ms`), so throughput at
   saturation gets full buckets while latency at low offered QPS stays
   bounded by the budget. `batching="fixed"` is the classic baseline
   (wait for a full batch, however long that takes) the `servingSlo`
   bench compares against; results are bit-identical across all three
   modes because the kernels are row-wise and the pad rows are copies of
   real rows. Requests carry an optional `tenant`: a forming batch never
   coalesces across tenants, each tenant may route to its own model via
   a `data.modelstore.ModelStore` (HBM-paged under an LRU byte budget —
   far more models than fit on device serve from one mesh, zero
   recompiles on page-in because model tensors are runtime operands),
   and per-tenant reject-policy quota gates keep one tenant's overload
   from starving another (docs/serving.md).

Pull-loop (`serve`) results are yielded IN ORDER. Push-loop results
retire in dispatch order, which is submission order WITHIN a tenant
(forming batches flush FIFO per tenant); across tenants, coalescing may
legitimately reorder. A batch's guard failure (e.g. Bucketizer
handleInvalid='error') raises when that batch is yielded — at most
`in_flight` batches later than the eager path would have raised, never
reordered and never dropped. When the consumer abandons `serve` early (a
`close()`/GeneratorExit) or a deferred guard error terminates it, the
still-in-flight window is drained and released — no staged device buffers
or queue slots leak (`serving.cancelled` counts the released batches).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import config, flow
from .ckpt import faults
from .obs import hist, memledger, timeline, tracing
from .parallel.prefetch import next_bucket, pad_rows, slice_rows, stage_to_device
from .pipeline import PipelineModel, _drain_guards
from .table import SparseBatch, Table
from .utils import metrics

__all__ = [
    "MicroBatchServer",
    "ServerHealth",
    "ServerOverloaded",
    "ServeResult",
    "serve_stream",
]

# The bucket schedule and repeat-last-row pad live in
# parallel/prefetch.py, shared with the stream-training staging paths —
# same policy, same guard-safety argument, one implementation.
_next_bucket, _pad_rows, _slice_rows = next_bucket, pad_rows, slice_rows

BATCHING_MODES = ("request", "fixed", "continuous")


class ServerOverloaded(flow.ChannelRejected):
    """`submit` fast-fail: the admission queue (or the submitting
    tenant's quota gate — `channel` = `serving.tenant.<name>`) is full.
    Carries the live queue depth and capacity (inherited from
    `flow.ChannelRejected`) so a client can back off / divert instead of
    parsing a message."""


@dataclass
class ServeResult:
    """One retired request from the push API, FIFO per tenant.
    `status` is `"ok"`, `"late"` (finished past its deadline), `"expired"`
    (deadline passed before dispatch — no compute paid, `table` is None)
    or `"error"` (`error` holds the exception; the stream continues)."""

    seq: int
    status: str
    table: Optional[Table] = None
    error: Optional[BaseException] = None
    tenant: Optional[str] = None


@dataclass
class ServerHealth:
    """Point-in-time server snapshot — the serving analogue of
    `DeviceEpochCache.stats`: every overload decision the server made,
    queriable without scraping the metrics registry."""

    inFlight: int  # window capacity
    windowDepth: int  # transformed-but-undrained batches right now
    admissionCapacity: int
    admissionDepth: int  # submitted-but-undispatched requests right now
    submitted: int  # requests accepted by submit()
    rejected: int  # submits refused at the door (ServerOverloaded)
    completed: int  # results delivered (any status)
    expired: int  # shed before dispatch: deadline already passed
    late: int  # delivered after their deadline
    errors: int  # per-request failures delivered as status "error"
    retries: int  # transient-fault retries paid by batch dispatch
    cancelled: int  # in-flight batches released by an early serve() exit
    bucketsSeen: int
    emaBatchMs: float  # dispatch trailing-mean latency (watchdog EMA)
    stragglers: int  # dispatches flagged beyond straggler_factor x mean
    # HBM ledger view (obs/memledger.py): total ledgered device-resident
    # bytes and the global peak watermark at snapshot time — memory sits
    # on the SLO surface next to the stage latencies, because the paging
    # work (ROADMAP item 3) is graded against exactly these numbers
    hbmLiveBytes: int = 0
    hbmPeakBytes: int = 0
    # per-stage latency percentiles from obs/hist.py (p50/p90/p99/p999 +
    # count per stage). EVERY stage label is present; a stage with zero
    # observations maps to None — never percentiles interpolated from an
    # empty bucket array (the Prometheus exporter likewise skips empty
    # histograms entirely)
    stageLatencyMs: Dict[str, Optional[Dict[str, float]]] = None
    # per-tenant quota-gate view: {tenant: {admitted, rejected, depth,
    # capacity}} for every tenant that has a quota gate (empty when no
    # tenant quotas are configured) — the fairness soak reads this
    tenantAdmission: Dict[str, Dict[str, int]] = None
    # attached ModelStore stats (models/resident/bytes/hits/misses/
    # evictions) or None when the server serves a single model
    modelStore: Optional[Dict[str, int]] = None

    #: The serving stage-attribution histograms (obs/hist.py names, all
    #: in milliseconds): queue-wait (submit -> dequeue), forming wait
    #: (dequeue -> the coalesced batch's flush; continuous/fixed modes
    #: only), batch formation (pad + H2D upload), dispatch (fused-plan
    #: launch), readback (the one blocking guard drain), and the
    #: remaining deadline margin at delivery (clamped at 0; lateness
    #: lands in `serving.lateByMs` and the deadlineMiss.late counter).
    STAGES = (
        ("queueWait", "serving.queueWaitMs"),
        ("formWait", "serving.formWaitMs"),
        ("batchForm", "serving.batchFormMs"),
        ("dispatch", "serving.dispatchMs"),
        ("readback", "serving.readbackMs"),
        ("deadlineMargin", "serving.deadlineMarginMs"),
    )


class _Forming:
    """One tenant's forming batch: requests coalescing toward a bucket.
    `flush_at` is the earliest member's forming deadline — `inf` under
    fixed batching (only a full bucket or server close flushes)."""

    __slots__ = ("tenant", "sig", "reqs", "rows", "flush_at")

    def __init__(self, tenant, sig):
        self.tenant = tenant
        self.sig = sig
        self.reqs: List[Tuple[int, Table, Optional[float], float]] = []
        self.rows = 0
        self.flush_at = float("inf")

    def add(self, seq: int, batch: Table, deadline: Optional[float], flush_at: float) -> None:
        self.reqs.append((seq, batch, deadline, time.monotonic()))
        self.rows += batch.num_rows
        self.flush_at = min(self.flush_at, flush_at)


class MicroBatchServer:
    """Drives fused transform plans over a batch stream.

    `in_flight` bounds the transformed-but-undrained window (default
    `config.serving_in_flight`); `buckets` optionally pins the padded
    batch-shape schedule (sorted ascending), otherwise batches pad to the
    next power of two. `device_input=True` uploads each padded batch's
    numeric host columns to device HBM before dispatch, so the whole
    pipeline — upload included — runs ahead of the previous batch's drain.
    `admission` bounds the push API's submit queue (default
    `config.serving_admission`); `deadline_ms` is the default per-request
    deadline (None = none); `retries` the transient-fault retry budget for
    batch dispatch (default `config.transient_retries`).

    Batching policy (`batching`): `"request"` (default) dispatches every
    submitted batch alone; `"continuous"` coalesces per-tenant forming
    batches that flush on bucket-full OR forming-budget expiry
    (`form_budget_ms`, default `config.serving_form_budget_ms`);
    `"fixed"` flushes only on bucket-full (the classic fixed-batch
    baseline). `form_rows` is the target bucket (default: the largest
    configured bucket, else 64).

    Multi-tenancy: pass a `data.modelstore.ModelStore` as `store` and
    submit with `tenant=<key>` — each request dispatches against its
    tenant's (HBM-paged) model. Per-tenant admission quotas come from
    the store's registrations or the `tenant_quotas` mapping; a tenant
    past its quota gets `ServerOverloaded` without consuming shared
    admission capacity.

    Two consumption styles:

    - `serve(stream)` — the pull loop: the caller owns pacing, the window
      gives lossless credit-based backpressure (the `block` policy).
    - `submit(batch)` + `results()` — the push loop: a dispatch worker
      consumes an admission queue with the `reject` policy; `submit`
      raises `ServerOverloaded` once `admission` requests wait.
    """

    def __init__(
        self,
        model: Optional[PipelineModel] = None,
        in_flight: Optional[int] = None,
        buckets: Optional[Sequence[int]] = None,
        device_input: bool = True,
        admission: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        retries: Optional[int] = None,
        lifecycle=None,
        batching: str = "request",
        form_rows: Optional[int] = None,
        form_budget_ms: Optional[float] = None,
        store=None,
        tenant_quotas: Optional[Dict[str, int]] = None,
    ):
        if model is None and store is None:
            raise TypeError("MicroBatchServer needs a model, a ModelStore, or both")
        if model is not None and not isinstance(model, PipelineModel):
            raise TypeError(f"MicroBatchServer serves a PipelineModel, got {type(model).__name__}")
        if batching not in BATCHING_MODES:
            raise ValueError(f"unknown batching mode {batching!r} (one of {BATCHING_MODES})")
        self.model = model
        self.store = store
        self.batching = batching
        self.in_flight = max(1, int(in_flight if in_flight is not None else config.serving_in_flight))
        self.buckets = sorted(int(b) for b in buckets) if buckets else None
        self.form_rows = max(
            1,
            int(
                form_rows
                if form_rows is not None
                else (self.buckets[-1] if self.buckets else 64)
            ),
        )
        self.form_budget_ms = (
            form_budget_ms if form_budget_ms is not None else config.serving_form_budget_ms
        )
        self.device_input = device_input
        self.admission = max(
            1, int(admission if admission is not None else config.serving_admission)
        )
        self.deadline_ms = deadline_ms if deadline_ms is not None else config.serving_deadline_ms
        self.retries = retries
        # optional lifecycle.ModelLifecycle: every retired batch's guard
        # outcome feeds its sliding health window, so a run of guard
        # errors (a bad promotion that slipped the gate) triggers the
        # automatic rollback WITHOUT restarting this server — the swap is
        # a pointer exchange the next batch picks up
        self.lifecycle = lifecycle
        self.watchdog = flow.StragglerWatchdog("serving.batch")
        self._tenant_quotas = dict(tenant_quotas) if tenant_quotas else {}
        self._tenant_gates: Dict[str, flow.BoundedChannel] = {}
        self._buckets_seen: set = set()
        self._counts: Dict[str, int] = {
            "completed": 0,
            "expired": 0,
            "late": 0,
            "errors": 0,
            "retries": 0,
            "cancelled": 0,
        }
        self._window: Optional[flow.BoundedChannel] = None  # latest serve window
        self._requests: Optional[flow.BoundedChannel] = None
        self._out: Optional[flow.BoundedChannel] = None
        self._worker = None
        self._start_lock = threading.Lock()
        self._seq = 0

    # -- batch staging -------------------------------------------------------
    def _stage_batch(self, batch: Table) -> Tuple[Table, int]:
        """Pad `batch` to its bucket and (optionally) upload numeric host
        columns — the H2D leg of the double buffer. All uploadable columns
        go through ONE `device_put` call (per-column puts would each pay a
        dispatch; on a remote-attached device, a round trip)."""
        n = batch.num_rows
        bucket = _next_bucket(n, self.buckets)
        self._buckets_seen.add(bucket)
        cols: Dict[str, Any] = {}
        uploads: Dict[str, Any] = {}
        for name in batch.column_names:
            col = _pad_rows(batch.column(name), n, bucket)
            if self.device_input and self._uploadable(col):
                uploads[name] = col
            else:
                cols[name] = col
        if uploads:
            from .table import register_device_pytrees

            register_device_pytrees()  # SparseBatch uploads as a pytree
            # accounted (h2d.bytes/count) + ledgered: the in-flight window
            # holds these buffers until the batch retires, so `serving`
            # residency tracks the window depth live
            uploads = stage_to_device(uploads, category="serving")
        return Table(
            {name: uploads.get(name, cols.get(name)) for name in batch.column_names}
        ), n

    @staticmethod
    def _uploadable(col) -> bool:
        if isinstance(col, SparseBatch):
            return isinstance(col.indices, np.ndarray)
        return (
            isinstance(col, np.ndarray)
            and col.dtype != object
            and col.dtype.kind not in ("U", "S")
        )

    def _model_for(self, tenant: Optional[str]) -> PipelineModel:
        """Resolve a request's model: the tenant's store entry (paged in
        on the spot — an LRU hit is a dict touch, a miss stages through
        the accounted funnel) or the server-wide default."""
        if self.store is not None and tenant is not None:
            return self.store.acquire(tenant)
        if self.model is None:
            raise TypeError(
                "MicroBatchServer has no default model: submit with tenant= "
                "or construct with model="
            )
        return self.model

    def _dispatch(self, batch: Table, index: int, model: Optional[PipelineModel] = None):
        """Stage + dispatch one batch under the transient-retry budget
        and the straggler watchdog. The `serving.batch` fault site sits
        inside the retried unit, so a `faults.flaky` plan exercises the
        retry path end to end; staging re-runs with the dispatch (an
        upload that failed mid-flight cannot be trusted half-done)."""
        served = model if model is not None else self._model_for(None)

        def attempt():
            faults.tick("serving.batch")
            t0 = time.perf_counter()
            staged, n = self._stage_batch(batch)
            t1 = time.perf_counter()
            out, pending = served.transform_deferred(staged)
            t2 = time.perf_counter()
            # stage attribution (obs/hist.py): where a request's latency
            # sits BEFORE the blocking drain — the serving mirror of the
            # training loop's dispatch-wall split
            hist.record("serving.batchFormMs", (t1 - t0) * 1000.0)
            hist.record("serving.dispatchMs", (t2 - t1) * 1000.0)
            if timeline.enabled():
                timeline.record_complete(
                    timeline.LANE_SERVING,
                    "serving.batchForm",
                    int(t0 * 1e9),
                    int((t1 - t0) * 1e9),
                    index=index,
                )
                timeline.record_complete(
                    timeline.LANE_SERVING,
                    "serving.dispatch",
                    int(t1 * 1e9),
                    int((t2 - t1) * 1e9),
                    index=index,
                )
            return out, pending, n

        with tracing.span("serving.batch", index=index, op="dispatch"):
            with self.watchdog.observe():
                return flow.with_retries(
                    attempt,
                    site="serving.batch",
                    retries=self.retries,
                    on_retry=lambda e, a: self._count("retries"),
                )

    def _count(self, key: str, n: int = 1) -> None:
        self._counts[key] = self._counts.get(key, 0) + n

    # -- warmup: the no-compile serving SLA ----------------------------------
    @staticmethod
    def _example_rows(example: Table, rows: int) -> Table:
        """Resize an example batch to exactly `rows` rows (slice down or
        repeat-last-row pad up) so its staged form lands on one bucket."""
        cols: Dict[str, Any] = {}
        n = example.num_rows
        for name in example.column_names:
            col = example.column(name)
            cols[name] = (
                _slice_rows(col, rows) if n >= rows else _pad_rows(col, n, rows)
            )
        return Table(cols)

    def warmup(
        self,
        example: Table,
        tenants: Optional[Sequence[Optional[str]]] = None,
        buckets: Optional[Sequence[int]] = None,
    ) -> Dict[str, float]:
        """Drive every (tenant x bucket) serving program once ahead of
        traffic, so the first real request finds its program resident.

        `example` is a schema template (one real batch — column names,
        dtypes, sparse layouts); each declared bucket gets a synthetic
        batch of exactly that many rows dispatched through the normal
        `_dispatch` funnel, which pages the tenant's model in through
        the ModelStore and compiles (or bank-loads) the fused segment
        program. With an active AOT program bank
        (`config.program_bank_dir`, compilebank.py) the compiled
        programs back-fill the bank, so the NEXT process's warmup is
        pure warm-loads — zero traces, zero XLA compiles — and its
        first request meets the no-compile SLA (`aotColdStart` bench
        entry asserts exactly this).

        Returns {"programs", "warmupMs", "bankHits", "bankMisses"} for
        the run; a guard tripped by synthetic rows is swallowed (the
        program is compiled either way — warmup must never take the
        server down)."""
        from .utils.metrics import snapshot_delta

        if buckets is None:
            buckets = self.buckets or [_next_bucket(self.form_rows, None)]
        buckets = sorted({int(b) for b in buckets})
        if tenants is None:
            tenants = list(self.store.keys()) if self.store is not None else [None]
        if self.store is not None:
            # page every tenant's model in first: warmup compiles against
            # resident model operands exactly as live dispatches will
            self.store.prefetch([t for t in tenants if t is not None], wait=True)
        t0 = time.perf_counter()
        before = metrics.snapshot()
        programs = 0
        for tenant in tenants:
            model = self._model_for(tenant)
            for bucket in buckets:
                synth = self._example_rows(example, bucket)
                try:
                    out, pending, n = self._dispatch(synth, index=-1, model=model)
                    self._finish(out, pending, n)
                except ValueError:
                    pass  # a guard fired on the synthetic rows; program is live
                programs += 1
        wall_ms = (time.perf_counter() - t0) * 1000.0
        metrics.record_time("serving.warmup", wall_ms / 1000.0)
        delta = snapshot_delta(before, metrics.snapshot())["counters"]
        return {
            "programs": float(programs),
            "warmupMs": wall_ms,
            "bankHits": float(delta.get("bank.hits", 0)),
            "bankMisses": float(delta.get("bank.misses", 0)),
        }

    def _finish(self, out: Table, pending: List[Tuple[str, Any]], n: int) -> Table:
        """Retire one batch from the in-flight window: ONE packed guard
        readback (the batch's only blocking sync), then slice the padding
        off on device. The guard outcome feeds the attached lifecycle's
        health window (rollback trigger)."""
        t0 = time.perf_counter()
        try:
            _drain_guards(pending)
        except Exception as e:
            if self.lifecycle is not None:
                self.lifecycle.record_guard_error(e)
            raise
        finally:
            dt = time.perf_counter() - t0
            hist.record("serving.readbackMs", dt * 1000.0)
            if timeline.enabled():
                timeline.record_complete(
                    timeline.LANE_SERVING,
                    "serving.readback",
                    int(t0 * 1e9),
                    int(dt * 1e9),
                )
        if self.lifecycle is not None:
            self.lifecycle.record_serve_ok()
        if out.num_rows == n:
            return out
        return Table({name: _slice_rows(out.column(name), n) for name in out.column_names})

    def _release(self, window: flow.BoundedChannel) -> None:
        """Early-exit cleanup: drop every still-in-flight batch — staged
        device buffers and pending guard handles release with their
        references, and the window's queue slots free — so an abandoned
        serve() (consumer close, deferred-guard error) leaks nothing.
        The abandoned guards are never drained: raising NEW errors out of
        a generator teardown would mask the one the consumer saw."""
        leaked = window.cancel()
        if leaked:
            metrics.inc_counter("serving.cancelled", len(leaked))
            self._count("cancelled", len(leaked))
        metrics.set_gauge("serving.buckets", len(self._buckets_seen))

    # -- the pull serving loop ----------------------------------------------
    def serve(self, stream: Iterable[Table]) -> Iterator[Table]:
        """Transform every batch of `stream`, yielding output Tables in
        input order. Output columns may be device-resident; callers that
        need host values materialize them (that readback is theirs)."""
        window = flow.BoundedChannel(self.in_flight, policy=flow.BLOCK, name="serving.window")
        self._window = window
        num_batches = 0
        metrics.set_gauge("serving.in_flight", self.in_flight)
        try:
            for batch in stream:
                entry = self._dispatch(batch, num_batches)
                if not window.offer(entry):  # window full: retire the oldest
                    # tpulint: disable=untimed-wait -- single-threaded pull loop: offer() just returned False, so the window is non-empty and get() cannot block
                    yield self._finish(*window.get())
                    window.offer(entry)
                num_batches += 1
                metrics.inc_counter("serving.batches")
                metrics.inc_counter("serving.records", entry[2])
                metrics.set_gauge("serving.buckets", len(self._buckets_seen))
            while len(window):
                # tpulint: disable=untimed-wait -- single-threaded pull loop: guarded by len(window) > 0, get() cannot block
                yield self._finish(*window.get())
        finally:
            self._release(window)

    # -- the push serving loop: admission control + deadlines ----------------
    def start(self) -> None:
        """Bring up the dispatch worker and its channels (idempotent;
        `submit` auto-starts). Locked double-check: a `results()`
        consumer thread and the first `submit()` race here, and two
        winners would each spawn a dispatch worker over its own channel
        pair — the loser's results would emit into an orphaned stream."""
        if self._worker is not None:
            return
        with self._start_lock:
            if self._worker is not None:
                return
            self._requests = flow.BoundedChannel(
                self.admission, policy=flow.REJECT, name="serving.admit"
            )
            # results buffer: sized so a retired batch never blocks the
            # worker while the admission queue and window both stay full —
            # the consumer's pull pace backpressures through it. Forming
            # batches can coalesce many admitted requests into one window
            # entry, so the retire fan-out is still bounded by `admission`
            self._out = flow.BoundedChannel(
                self.admission + self.in_flight + 1, policy=flow.BLOCK, name="serving.results"
            )
            metrics.set_gauge("serving.in_flight", self.in_flight)
            # assigned last: `submit`/`results` treat a non-None worker as
            # "channels are live", so this publish orders after them
            self._worker = flow.spawn(self._run, name="serving.dispatch")

    # -- per-tenant quota gates ----------------------------------------------
    def _quota_gate(self, tenant: Optional[str]) -> Optional[flow.BoundedChannel]:
        """The tenant's reject-policy admission gate (created lazily from
        `tenant_quotas` or the store's registration), or None for
        unquota'd tenants. Each admitted request holds one credit until
        it leaves the queue+forming pipeline (dispatch/expiry)."""
        if tenant is None:
            return None
        gate = self._tenant_gates.get(tenant)
        if gate is None:
            quota = self._tenant_quotas.get(tenant)
            if quota is None and self.store is not None and tenant in self.store:
                quota = self.store.quota(tenant)
            if quota is None:
                return None
            gate = flow.BoundedChannel(
                max(1, int(quota)), policy=flow.REJECT, name=f"serving.tenant.{tenant}"
            )
            self._tenant_gates[tenant] = gate
        return gate

    def _quota_release(self, tenant: Optional[str]) -> None:
        if tenant is None:
            return
        gate = self._tenant_gates.get(tenant)
        if gate is None:
            return
        try:
            gate.get(timeout=0)
        except (TimeoutError, flow.ChannelClosed):
            pass

    def submit(
        self,
        batch: Table,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> int:
        """Admit one batch, returning its sequence number. Raises
        `ServerOverloaded` (with the live queue depth) when `admission`
        requests already wait — or when `tenant`'s quota gate is full —
        the typed fast-fail of the `reject` policy. `deadline_ms`
        overrides the server default; `tenant` routes to that tenant's
        store model and quota."""
        if self._worker is None:
            self.start()
        if self.store is not None and tenant is not None and tenant not in self.store:
            raise KeyError(f"tenant {tenant!r} is not registered in the model store")
        ms = deadline_ms if deadline_ms is not None else self.deadline_ms
        deadline = None if ms is None else time.monotonic() + ms / 1000.0
        seq = self._seq
        gate = self._quota_gate(tenant)
        if gate is not None:
            try:
                gate.put(seq)
            except flow.ChannelRejected as e:
                metrics.inc_counter("serving.rejected")
                metrics.inc_counter(f"serving.rejected.tenant.{tenant}")
                raise ServerOverloaded(e.channel, e.depth, e.capacity) from None
        try:
            self._requests.put((seq, tenant, batch, deadline, time.monotonic()))
        except flow.ChannelRejected as e:
            if gate is not None:  # refund the tenant credit
                self._quota_release(tenant)
            metrics.inc_counter("serving.rejected")
            raise ServerOverloaded(e.channel, e.depth, e.capacity) from None
        self._seq += 1
        metrics.inc_counter("serving.batches")
        metrics.inc_counter("serving.records", batch.num_rows)
        return seq

    def close(self) -> None:
        """No more submits; the worker drains what was admitted (flushing
        any partial forming batches) and closes the results stream."""
        if self._requests is not None:
            self._requests.close()

    def results(self) -> Iterator[ServeResult]:
        """Retired requests (`ServeResult`), FIFO per tenant; ends when
        `close()` has been called and every admitted request retired."""
        if self._worker is None:
            self.start()
        yield from self._out

    def health(self) -> ServerHealth:
        """A `ServerHealth` snapshot of queues, overload decisions, retry
        spend, dispatch latency, and the per-stage latency percentiles
        (`stageLatencyMs`, from the obs/hist.py histograms)."""
        stage_latency: Dict[str, Optional[Dict[str, float]]] = {}
        for label, hist_name in ServerHealth.STAGES:
            p = hist.percentiles(hist_name)
            # a stage with zero observations reports None — percentiles
            # interpolated from an empty bucket array would be fiction
            stage_latency[label] = (
                None
                if p is None
                else {k: p[k] for k in ("count", "p50", "p90", "p99", "p999")}
            )
        tenants: Dict[str, Dict[str, int]] = {}
        for tenant, gate in self._tenant_gates.items():
            tenants[tenant] = {
                "admitted": gate.stats.puts,
                "rejected": gate.stats.rejected,
                "depth": len(gate),
                "capacity": gate.capacity,
            }
        window_depth = len(self._window) if self._window is not None else 0
        adm_depth = len(self._requests) if self._requests is not None else 0
        rejected = (
            self._requests.stats.rejected if self._requests is not None else 0
        )
        rejected += sum(g.stats.rejected for g in self._tenant_gates.values())
        submitted = self._requests.stats.puts if self._requests is not None else 0
        return ServerHealth(
            inFlight=self.in_flight,
            windowDepth=window_depth,
            admissionCapacity=self.admission,
            admissionDepth=adm_depth,
            submitted=submitted,
            rejected=rejected,
            completed=self._counts["completed"],
            expired=self._counts["expired"],
            late=self._counts["late"],
            errors=self._counts["errors"],
            retries=self._counts["retries"],
            cancelled=self._counts["cancelled"],
            bucketsSeen=len(self._buckets_seen),
            emaBatchMs=self.watchdog.trailing_mean_s * 1000.0,
            stragglers=metrics.get_counter("flow.straggler.serving.batch", 0),
            hbmLiveBytes=memledger.live_bytes(),
            hbmPeakBytes=memledger.peak_bytes(),
            stageLatencyMs=stage_latency,
            tenantAdmission=tenants,
            modelStore=self.store.stats if self.store is not None else None,
        )

    def _run(self) -> None:
        """Dispatch worker: admission queue → (forming) → window →
        results, deadlines enforced at every hop. Any worker-level
        failure closes the results channel with the error — consumers
        re-raise instead of hanging."""
        window = flow.BoundedChannel(self.in_flight, policy=flow.BLOCK, name="serving.window")
        self._window = window
        try:
            if self.batching == "request":
                self._run_per_request(window)
            else:
                self._run_forming(window)
            while len(window):
                # tpulint: disable=untimed-wait -- dispatch-worker-local window: guarded by len(window) > 0, get() cannot block
                self._retire(window.get())
            self._out.close()
        except BaseException as e:  # worker death must not strand consumers
            self._out.close(error=e)
        finally:
            self._release(window)

    def _run_per_request(self, window: flow.BoundedChannel) -> None:
        """The classic loop: every submitted batch dispatches alone."""
        for seq, tenant, batch, deadline, submitted in self._requests:
            hist.record(
                "serving.queueWaitMs", (time.monotonic() - submitted) * 1000.0
            )
            self._quota_release(tenant)
            if deadline is not None and time.monotonic() > deadline:
                # shed BEFORE paying staging/compute: the client
                # already gave up on this request. Cause-attributed:
                # expired-IN-QUEUE (vs late-after-dispatch below) —
                # `serving.deadlineMiss` stays the compatibility sum
                metrics.inc_counter("serving.deadlineMiss")
                metrics.inc_counter("serving.deadlineMiss.expired")
                self._count("expired")
                self._emit(ServeResult(seq, "expired", tenant=tenant))
                continue
            try:
                model = self._model_for(tenant)
                out, pending, n = self._dispatch(batch, seq, model=model)
            except Exception as e:  # per-request failure: stream survives
                self._count("errors")
                self._emit(ServeResult(seq, "error", error=e, tenant=tenant))
                continue
            entry = (((seq, deadline, 0, n, tenant),), out, pending, n)
            if not window.offer(entry):
                # tpulint: disable=untimed-wait -- dispatch-worker-local window: offer() just returned False, so the window is non-empty and get() cannot block
                self._retire(window.get())
                window.offer(entry)

    # -- continuous batching: the forming buffer -----------------------------
    def _run_forming(self, window: flow.BoundedChannel) -> None:
        """Admit requests into per-tenant FORMING batches mid-flight. A
        batch flushes on bucket-full (`form_rows`), forming-budget expiry
        (continuous mode), an incompatible next request (per-tenant FIFO
        is preserved: the old batch always dispatches first), or close."""
        forming: Dict[Optional[str], _Forming] = {}
        while True:
            timeout = None
            if forming:
                soonest = min(g.flush_at for g in forming.values())
                if soonest != float("inf"):
                    timeout = max(0.0, soonest - time.monotonic())
            if timeout is None and len(window):
                # no flush pending but batches sit in flight: poll the
                # queue and, when it is empty, take the blocking readback
                # NOW — a finished result must not wait for the NEXT
                # arrival (or close) to retire. Under load the poll finds
                # a queued request and the double buffer stays pipelined.
                timeout = 0.0
            try:
                req = self._requests.get(timeout=timeout)
            except TimeoutError:  # a forming budget expired: flush what's due
                self._flush_due(forming, window)
                if timeout == 0.0 and len(window):
                    # tpulint: disable=untimed-wait -- dispatch-worker-local window: guarded by len(window) > 0, get() cannot block
                    self._retire(window.get())
                continue
            except flow.ChannelClosed:
                break
            self._admit_forming(req, forming, window)
            self._flush_due(forming, window)
        for tenant in list(forming):  # close(): partial batches still dispatch
            self._flush_group(forming.pop(tenant), window)

    def _form_flush_at(self, deadline: Optional[float]) -> float:
        """A request's forming deadline: flush when its deadline margin
        hits the forming budget (it must still dispatch + compute inside
        the margin), and never hold a request in FORMING longer than the
        budget itself. Both legs are measured from admission into
        forming, not from submit: under a backlog the queue wait alone
        exceeds the budget, and an already-blown margin cannot be saved
        by flushing a tiny batch — it would only shrink every batch to
        ~1 request and collapse saturated goodput, which is exactly the
        regime where full buckets matter most. Fixed batching never
        flushes on time — only on a full bucket."""
        if self.batching == "fixed":
            return float("inf")
        budget = self.form_budget_ms / 1000.0
        now = time.monotonic()
        flush_at = now + budget
        if deadline is not None and deadline - budget > now:
            flush_at = min(flush_at, deadline - budget)
        return flush_at

    @staticmethod
    def _batch_sig(batch: Table) -> Optional[tuple]:
        """Coalescing signature: two batches may share a forming batch iff
        their column names, kinds, dtypes and trailing shapes all match
        (row-wise kernels make the concatenation semantically the union
        of the requests). None = host-concat is unsafe (device-resident
        or object columns): the request dispatches alone."""
        sig = []
        for name in batch.column_names:
            col = batch.column(name)
            if isinstance(col, SparseBatch):
                if not isinstance(col.indices, np.ndarray):
                    return None
                sig.append(
                    ("sparse", name, col.size, col.indices.shape[1:], str(col.values.dtype))
                )
            elif isinstance(col, np.ndarray) and col.dtype != object:
                sig.append(("np", name, col.shape[1:], str(col.dtype)))
            else:
                return None
        return tuple(sig)

    @staticmethod
    def _concat_batches(batches: List[Table]) -> Table:
        """Host-side concatenation of signature-compatible batches — the
        forming batch the fused plan sees as ONE bucket-padded dispatch."""
        cols: Dict[str, Any] = {}
        for name in batches[0].column_names:
            vals = [b.column(name) for b in batches]
            first = vals[0]
            if isinstance(first, SparseBatch):
                cols[name] = SparseBatch(
                    first.size,
                    np.concatenate([v.indices for v in vals], axis=0),
                    np.concatenate([v.values for v in vals], axis=0),
                )
            else:
                cols[name] = np.concatenate(vals, axis=0)
        return Table(cols)

    def _admit_forming(
        self,
        req: tuple,
        forming: Dict[Optional[str], _Forming],
        window: flow.BoundedChannel,
    ) -> None:
        seq, tenant, batch, deadline, submitted = req
        now = time.monotonic()
        hist.record("serving.queueWaitMs", (now - submitted) * 1000.0)
        if deadline is not None and now > deadline:
            self._quota_release(tenant)
            metrics.inc_counter("serving.deadlineMiss")
            metrics.inc_counter("serving.deadlineMiss.expired")
            self._count("expired")
            self._emit(ServeResult(seq, "expired", tenant=tenant))
            return
        sig = self._batch_sig(batch)
        group = forming.get(tenant)
        n = batch.num_rows
        if group is not None and (
            sig is None or group.sig != sig or group.rows + n > self.form_rows
        ):
            # incompatible or over-target: the older batch flushes FIRST,
            # preserving per-tenant FIFO
            self._flush_group(forming.pop(tenant), window)
            group = None
        if sig is None:  # non-coalescable: dispatch alone, right now
            solo = _Forming(tenant, None)
            solo.add(seq, batch, deadline, flush_at=0.0)
            self._flush_group(solo, window)
            return
        if group is None:
            group = forming[tenant] = _Forming(tenant, sig)
        group.add(seq, batch, deadline, self._form_flush_at(deadline))
        if group.rows >= self.form_rows:  # bucket full: go now
            self._flush_group(forming.pop(tenant), window)

    def _flush_due(
        self, forming: Dict[Optional[str], _Forming], window: flow.BoundedChannel
    ) -> None:
        now = time.monotonic()
        for tenant in [t for t, g in forming.items() if g.flush_at <= now]:
            self._flush_group(forming.pop(tenant), window)

    def _flush_group(self, group: _Forming, window: flow.BoundedChannel) -> None:
        """Dispatch one forming batch: concat members, one fused dispatch,
        one window entry carrying each member's row span so `_retire`
        hands every request ITS rows back."""
        now = time.monotonic()
        live: List[Tuple[int, Table, Optional[float]]] = []
        for seq, batch, deadline, admitted in group.reqs:
            self._quota_release(group.tenant)
            if deadline is not None and now > deadline:  # expired while forming
                metrics.inc_counter("serving.deadlineMiss")
                metrics.inc_counter("serving.deadlineMiss.expired")
                self._count("expired")
                self._emit(ServeResult(seq, "expired", tenant=group.tenant))
                continue
            hist.record("serving.formWaitMs", (now - admitted) * 1000.0)
            live.append((seq, batch, deadline))
        if not live:
            return
        merged = live[0][1] if len(live) == 1 else self._concat_batches([b for _, b, _ in live])
        parts: List[Tuple[int, Optional[float], int, int, Optional[str]]] = []
        offset = 0
        for seq, batch, deadline in live:
            parts.append((seq, deadline, offset, offset + batch.num_rows, group.tenant))
            offset += batch.num_rows
        try:
            model = self._model_for(group.tenant)
            out, pending, n = self._dispatch(merged, live[0][0], model=model)
        except Exception as e:  # whole forming batch fails per-request
            for seq, _, _ in live:
                self._count("errors")
                self._emit(ServeResult(seq, "error", error=e, tenant=group.tenant))
            return
        if len(live) > 1:
            metrics.inc_counter("serving.coalesced", len(live))
        entry = (tuple(parts), out, pending, n)
        if not window.offer(entry):
            # tpulint: disable=untimed-wait -- dispatch-worker-local window: offer() just returned False, so the window is non-empty and get() cannot block
            self._retire(window.get())
            window.offer(entry)

    @staticmethod
    def _slice_span(col, start: int, stop: int):
        if isinstance(col, SparseBatch):
            return SparseBatch(col.size, col.indices[start:stop], col.values[start:stop])
        return col[start:stop]

    @staticmethod
    def _to_host(col):
        if isinstance(col, SparseBatch):
            return SparseBatch(col.size, np.asarray(col.indices), np.asarray(col.values))
        return col if isinstance(col, np.ndarray) else np.asarray(col)

    def _retire(self, entry) -> None:
        """Retire one window entry: the single guard readback, then each
        member request gets its row span, deadline verdict, and result.

        Pad-undo and per-part span slicing happen on HOST: an eager
        device slice compiles one XLA program per distinct (shape, span)
        pair, and continuous forming produces an open-ended set of those
        — steady-state paging would keep compiling, breaking the
        zero-recompile contract the servingSlo bench pins. Push results
        are terminal per-request responses, so the one materialization
        here replaces the consumer's own later pull; an unpadded solo
        batch still retires device-resident, untouched."""
        parts, out, pending, n = entry
        padded = out.num_rows
        try:
            table = self._finish(out, pending, padded)
        except Exception as e:  # deferred guard error: per-request, in order
            for seq, _deadline, _start, _stop, tenant in parts:
                self._count("errors")
                self._emit(ServeResult(seq, "error", error=e, tenant=tenant))
            return
        sliced = len(parts) > 1 or n != padded
        if sliced:
            table = Table(
                {name: self._to_host(table.column(name)) for name in table.column_names}
            )
        now = time.monotonic()
        for seq, deadline, start, stop, tenant in parts:
            if not sliced:
                sub = table
            else:
                sub = Table(
                    {
                        name: self._slice_span(table.column(name), start, stop)
                        for name in table.column_names
                    }
                )
            status = "ok"
            if deadline is not None:
                margin_ms = (deadline - now) * 1000.0
                if margin_ms < 0:
                    # cause-attributed miss: finished LATE after dispatch
                    # (the compute was paid — contrast deadlineMiss.expired)
                    metrics.inc_counter("serving.deadlineMiss")
                    metrics.inc_counter("serving.deadlineMiss.late")
                    hist.record("serving.lateByMs", -margin_ms)
                    self._count("late")
                    status = "late"
                else:
                    hist.record("serving.deadlineMarginMs", margin_ms)
            self._emit(ServeResult(seq, status, table=sub, tenant=tenant))

    def _emit(self, result: ServeResult) -> None:
        self._count("completed")
        try:
            self._out.put(result)
        except flow.ChannelClosed:  # consumer cancelled results(): drop
            pass


def serve_stream(
    model: PipelineModel,
    stream: Iterable[Table],
    in_flight: Optional[int] = None,
    buckets: Optional[Sequence[int]] = None,
) -> List[Table]:
    """One-shot convenience: serve the whole stream, collect the outputs."""
    return list(MicroBatchServer(model, in_flight=in_flight, buckets=buckets).serve(stream))
