"""Comm/compute overlap scheduling for the iterative training loops.

The default training programs let GSPMD place one monolithic all-reduce
per batch at the point the gradient contraction completes: the reduction
sits on the critical path between batch b's backward and batch b+1's
forward, and nothing overlaps it. This module rebuilds the hot loops as
explicit-SPMD (`shard_map`) programs with a **carry-delayed apply**: the
loop carries the UNREDUCED per-shard gradient, and the reduction is
deferred to the top of the next epoch — batch b's gradient buckets reduce
(`collectives.all_reduce_sum_chunked`, ring-pipelined when configured)
while batch b+1's batch slice/gather work is already in flight, and on
hardware the async-collective pass hoists the bucket transfers under the
forward compute. Snap ML (arXiv:1803.06333) motivates exactly this
hierarchical chunk-and-overlap schedule.

Bit-parity is by construction, the same way the dispatch pipeline pins
chunked epochs (docs/performance.md §1): the reduction still happens
before the apply that consumes it, the chunked/sparse reduction is
bit-identical to the monolithic psum, and the per-epoch update order is
unchanged — so overlap mode produces bit-identical coefficients, stop
epochs, and criteria (pinned by tests/test_collective_chunks.py for dense
and sparse losses, tol early-stop included).

Sparse gradients additionally ride the SparCML index-value reduction
(`collectives.sparse_all_reduce_sum`) when their per-shard pair bytes are
below `config.collective_sparse_threshold` × the dense payload: the
(indices, values) pairs of the batch cross the links instead of the
densified `(dim,)` vector, so sparseWideLR gradient traffic scales with
nnz, not dim.

Gated by `config.collective_overlap` (see ops/optimizer.py and the KMeans
driver); compiled programs are cached per (mesh, loss, flags) so repeated
fits re-enter the same executable.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import collectives
from . import mesh as mesh_lib

_SGD_CACHE: Dict[Tuple, Callable] = {}
_SGD2D_CACHE: Dict[Tuple, Callable] = {}
_LLOYD_CACHE: Dict[Tuple, Callable] = {}


def clear_program_cache() -> None:
    _SGD_CACHE.clear()
    _SGD2D_CACHE.clear()
    _LLOYD_CACHE.clear()


def _config_key():
    """The trace-relevant collective knobs; part of every program cache key
    so flipping config recompiles instead of serving a stale schedule."""
    from .. import config

    return (
        config.resolve_chunk_bytes(None),
        bool(config.collective_ring),
        float(config.collective_sparse_threshold),
    )


def _local_pieces(X, y, w, coeff, loss_func, sparse_pairs: bool):
    """Per-shard loss pieces for one batch: (loss_sum_local, grad_local,
    wsum_local). `grad_local` is either the dense per-shard scatter/matmul
    partial — the exact local operand GSPMD would feed its psum — or, with
    `sparse_pairs`, the flattened (indices, values) contribution pairs for
    the index-value reduction."""
    if loss_func.sparse:
        from ..ops.losses import sparse_dot

        indices, values = X
        dot, safe, vals = sparse_dot(indices, values, coeff)
        loss, mult = loss_func.pointwise(dot, y, w)
        contrib = vals * mult[:, None]
        if sparse_pairs:
            grad_local = (jnp.ravel(safe), jnp.ravel(contrib))
        else:
            grad_local = (
                jnp.zeros_like(coeff).at[safe].add(contrib, mode="drop")
            )
    else:
        from ..ops.losses import dense_dot, dense_grad

        loss, mult = loss_func.pointwise(dense_dot(X, coeff), y, w)
        grad_local = dense_grad(X, mult)
    return jnp.sum(loss), grad_local, jnp.sum(w)


def _init_grad_local(coeff, num_rows: int, nnz: int, dtype, sparse_pairs: bool):
    """Zero gradient carry matching `_local_pieces`' output structure; a
    reduce of it is exactly the dense path's zero init gradient."""
    if sparse_pairs:
        return (
            jnp.zeros((num_rows * nnz,), jnp.int32),
            jnp.zeros((num_rows * nnz,), dtype),
        )
    return jnp.zeros_like(coeff)


def sgd_use_sparse_pairs(X_b, d: int, mesh: Mesh) -> bool:
    """Trace-time routing for the sparse SGD gradient: index-value pairs
    when the mesh actually reduces (>1 data shard) and the per-shard pair
    bytes beat the density threshold."""
    if not isinstance(X_b, tuple):
        return False
    shards = mesh_lib.num_data_shards(mesh)
    if shards <= 1:
        return False
    _, b_pad, nnz = X_b[0].shape
    itemsize = np.dtype(X_b[1].dtype).itemsize
    return collectives.sparse_reduce_wins(
        (b_pad // shards) * nnz, d, itemsize=itemsize
    )


def overlapped_sgd_train(
    mesh: Mesh,
    X_b,
    y_b,
    w_b,
    init_coeff,
    loss_func,
    hyper,
    check_labels: bool,
):
    """The bounded SGD iteration as one explicit-SPMD program with
    overlap-scheduled gradient reduction. Same contract as
    `ops.optimizer._sgd_train`: returns the packed
    [flag?, coeff, criteria, epochs] result vector.

    Schedule per epoch (vs. the eager program's reduce-at-batch-end):

        eager:    forward_b -> backward_b -> ALL-REDUCE -> apply -> fwd_{b+1}
        overlap:  forward_b -> backward_b -> carry local grad
                  ALL-REDUCE(grad_b) ∥ batch-slice/gather of b+1 -> apply -> fwd

    The per-epoch tol check still needs the reduced loss, so the (loss,
    wsum) SCALARS reduce every epoch (8 bytes — latency, not bandwidth);
    only the dim-proportional gradient is deferred and bucketed."""
    key = (
        mesh,
        loss_func,
        bool(check_labels),
        sgd_use_sparse_pairs(X_b, int(np.shape(init_coeff)[0]), mesh),
        _config_key(),
    )
    fn = _SGD_CACHE.get(key)
    if fn is None:
        fn = _build_sgd_program(mesh, loss_func, key[2], key[3])
        _SGD_CACHE[key] = fn
    return fn(X_b, y_b, w_b, init_coeff, hyper)


def _build_sgd_program(mesh: Mesh, loss_func, check_labels: bool, sparse_pairs: bool):
    from ..ops.optimizer import (
        _binomial_labels_ok,
        _index_batch,
        _pack_train_result,
        _unpack_hyper,
        _update_model,
    )

    axis = mesh_lib.DATA_AXIS
    batched = P(None, axis, None)
    x_spec = (batched, batched) if loss_func.sparse else batched
    in_specs = (x_spec, P(None, axis), P(None, axis), P(), P())

    def train(X_b, y_b, w_b, init_coeff, hyper):
        num_batches, b_local = y_b.shape
        d = init_coeff.shape[0]
        dtype = X_b[1].dtype if isinstance(X_b, tuple) else X_b.dtype
        nnz = X_b[0].shape[-1] if isinstance(X_b, tuple) else 0
        max_iter, tol, lr, reg, elastic_net = _unpack_hyper(hyper, dtype)

        def reduce_grad(g_local):
            if sparse_pairs:
                return collectives.sparse_all_reduce_sum(
                    g_local[0], g_local[1], d, axis
                )
            return collectives.all_reduce_sum_chunked(g_local, axis)

        def cond(state):
            _, _, _, epoch, criteria = state
            return jnp.logical_and(epoch < max_iter, criteria > tol)

        def body(state):
            coeff, g_local, wsum, epoch, _ = state
            # carry-delayed apply: batch (epoch-1)'s gradient reduces here,
            # where its buckets overlap this epoch's batch staging
            coeff = _update_model(
                coeff, reduce_grad(g_local), wsum, lr, reg, elastic_net
            )
            k = jnp.mod(epoch, num_batches)
            Xk = _index_batch(X_b, k)
            yk = lax.dynamic_index_in_dim(y_b, k, axis=0, keepdims=False)
            wk = lax.dynamic_index_in_dim(w_b, k, axis=0, keepdims=False)
            loss_local, g_local, wsum_local = _local_pieces(
                Xk, yk, wk, coeff, loss_func, sparse_pairs
            )
            # the tol check needs the reduced criteria every epoch: reduce
            # the two scalars now, leave the gradient in the carry
            sums = collectives.all_reduce_sum(
                jnp.stack([loss_local.astype(jnp.float32), wsum_local.astype(jnp.float32)]),
                axis,
            )
            wsum = sums[1].astype(dtype)
            criteria = sums[0] / jnp.maximum(sums[1], 1e-30)
            return (coeff, g_local, wsum, epoch + 1, criteria)

        init_state = (
            jnp.asarray(init_coeff, dtype),
            _init_grad_local(jnp.zeros((d,), dtype), b_local, nnz, dtype, sparse_pairs),
            jnp.asarray(0.0, dtype),
            jnp.asarray(0, jnp.int32),
            jnp.asarray(jnp.inf, jnp.float32),
        )
        coeff, g_local, wsum, epochs, criteria = lax.while_loop(cond, body, init_state)
        # the one-extra-update-after-termination of the reference
        # (SGD.java onIterationTerminated) reduces the final carry
        coeff = _update_model(coeff, reduce_grad(g_local), wsum, lr, reg, elastic_net)
        flag = None
        if check_labels:
            ok = _binomial_labels_ok(y_b)
            flag = collectives.all_reduce_min(ok, axis)
        return _pack_train_result(coeff, criteria, epochs, flag)

    mapped = collectives.shard_map_over(mesh, in_specs, P(), fn=train)
    # tpulint: disable=retrace-hazard -- overlap mode builds one program per fit by design (opt-in; caching keyed on mesh/shape is ROADMAP item 2)
    return jax.jit(mapped)


# ---------------------------------------------------------------------------
# true 2D (data × model) sparse SGD programs
# ---------------------------------------------------------------------------
# The feature-sharded training loop as explicit SPMD: the coefficient and
# gradient carries live as (d_local,) MODEL-axis slices (the per-device
# residency that makes beyond-HBM dims fit), batches stay DATA-sharded, and
# the per-epoch math is `ops.optimizer._sgd_chunk_impl` verbatim over the
# 2D loss variant (`ops.losses.feature_sharded_variant`) — whose collectives
# are axis-restricted: active-feature assembly psums over `model`, the
# SparCML gradient reduce over `data` only. The whole-fit flavor keeps the
# PR 13 ONE-dispatch + ONE-readback contract under sharding by packing the
# result as ONE MODEL-SHARDED array (per-shard block = [flag?, coeff_slice,
# criteria, epochs]) instead of `_pack_train_result`'s replicated
# concatenate: a full-d replicated pack would re-materialize the very
# vector the mesh exists to split (and `utils.packing.packed_device_get`'s
# device-side concatenate of mixed shardings is the GSPMD multi-axis
# miscompile `_pack_train_result` documents). `sgd2d_unpack_host` is the
# host-side inverse.


def sgd2d_whole_fit(mesh, X_b, y_b, w_b, carry, criteria, loss_func, hyper,
                    check_labels=False):
    """The entire 2D fit as ONE resident program: epoch loop to maxIter,
    barrier-pinned final update, model-sharded packed result. Returns
    (carry, criteria, packed) with the carry device-resident and sharded
    (coeff/grad = model-axis slices) for the fit-end snapshot — the PR 14
    coordinator's model-tag case."""
    key = (mesh, loss_func, "whole", bool(check_labels), _config_key())
    fn = _SGD2D_CACHE.get(key)
    if fn is None:
        fn = _build_sgd2d_program(mesh, loss_func, "whole", bool(check_labels))
        _SGD2D_CACHE[key] = fn
    return fn(X_b, y_b, w_b, carry, criteria, hyper)


def sgd2d_chunk(mesh, X_b, y_b, w_b, carry, criteria, loss_func, hyper, chunk_end):
    """Host-driven 2D epochs up to `chunk_end` for the checkpointed loop:
    same contract as `ops.optimizer._sgd_chunk` ((carry, criteria,
    packed[epoch, criteria])) with the carry staying model-sharded across
    snapshot boundaries. Always borrowing — the pre-chunk carry must stay
    readable for a pending snapshot write."""
    key = (mesh, loss_func, "chunk", False, _config_key())
    fn = _SGD2D_CACHE.get(key)
    if fn is None:
        fn = _build_sgd2d_program(mesh, loss_func, "chunk", False)
        _SGD2D_CACHE[key] = fn
    return fn(X_b, y_b, w_b, carry, criteria, hyper, chunk_end)


def sgd2d_unpack_host(host, num_model_shards: int, d_local: int,
                      has_flag: bool):
    """Host-side inverse of the model-sharded result pack: the readback is
    (num_model_shards * block,) with block = [flag?, coeff_slice, criteria,
    epochs]. The scalars are uniform across shards (they were psum'd over
    `data` and identical on every model shard); block 0's copies are
    authoritative. Returns (coeff, criteria, epochs, flag?)."""
    block = d_local + 2 + (1 if has_flag else 0)
    blocks = np.asarray(host).reshape(num_model_shards, block)
    off = 1 if has_flag else 0
    coeff = np.concatenate([blocks[s, off:off + d_local] for s in range(num_model_shards)])
    criteria = float(blocks[0, off + d_local])
    epochs = int(blocks[0, off + d_local + 1])
    flag = float(blocks[0, 0]) if has_flag else None
    return coeff, criteria, epochs, flag


def _build_sgd2d_program(mesh: Mesh, loss_func, flavor: str, check_labels: bool):
    from ..ops.losses import feature_sharded_variant
    from ..ops.optimizer import (
        _binomial_labels_ok,
        _sgd_chunk_impl,
        _unpack_hyper,
        _update_model,
    )

    data, model = mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS
    loss2d = feature_sharded_variant(loss_func)
    batched = P(None, data, None)
    carry_spec = (P(model), P(model), P(), P())
    base_in = ((batched, batched), P(None, data), P(None, data), carry_spec, P())

    if flavor == "chunk":

        def chunk(X_b, y_b, w_b, carry, criteria, hyper, chunk_end):
            return _sgd_chunk_impl(
                X_b, y_b, w_b, carry, criteria, loss2d, hyper, chunk_end
            )

        mapped = collectives.shard_map_over(
            mesh, base_in + (P(), P()), (carry_spec, P(), P()), fn=chunk
        )
    else:

        def whole(X_b, y_b, w_b, carry, criteria, hyper):
            dtype = X_b[1].dtype
            max_iter, _, lr, reg, elastic_net = _unpack_hyper(hyper, dtype)
            carry, criteria, _ = _sgd_chunk_impl(
                X_b, y_b, w_b, carry, criteria, loss2d, hyper, max_iter
            )
            # barrier-pinned final update, exactly `_sgd_whole_fit_impl`:
            # the one-extra-update must consume the MATERIALIZED loop carry
            # for bit-parity with the chunked path's host-side apply
            coeff, grad, wsum, epochs = lax.optimization_barrier(carry)
            final = _update_model(coeff, grad, wsum, lr, reg, elastic_net)
            dt = jnp.promote_types(final.dtype, jnp.float32)
            parts = [
                final.astype(dt),
                jnp.reshape(jnp.asarray(criteria).astype(dt), (1,)),
                jnp.reshape(jnp.asarray(epochs).astype(dt), (1,)),
            ]
            if check_labels:
                ok = collectives.all_reduce_min(_binomial_labels_ok(y_b), data)
                parts.insert(0, jnp.reshape(ok.astype(dt), (1,)))
            return carry, criteria, jnp.concatenate(parts)

        mapped = collectives.shard_map_over(
            mesh, base_in + (P(),), (carry_spec, P(), P(model)), fn=whole
        )
    # tpulint: disable=retrace-hazard -- one 2D program per (mesh, loss, flavor); cached in _SGD2D_CACHE so repeated fits re-enter the same executable
    return jax.jit(mapped)


def overlapped_lloyd_train(
    mesh: Mesh, X, weights, init_centroids, max_iter, measure_name: str
):
    """Lloyd's loop with the same carry-delayed schedule: the (k, d)+(k,)
    centroid-partial reduction of epoch e rides the chunked collective at
    the top of epoch e+1, overlapping the pairwise-distance matmul of the
    next assignment. Bit-identical to the eager `_lloyd_train` (the
    reduce is psum-bit-equal and the update order is unchanged)."""
    key = (mesh, measure_name, _config_key())
    fn = _LLOYD_CACHE.get(key)
    if fn is None:
        fn = _build_lloyd_program(mesh, measure_name)
        _LLOYD_CACHE[key] = fn
    return fn(X, weights, init_centroids, max_iter)


def _build_lloyd_program(mesh: Mesh, measure_name: str):
    from ..ops.distance import DistanceMeasure

    axis = mesh_lib.DATA_AXIS
    measure = DistanceMeasure.get_instance(measure_name)

    def train(X, weights, init_centroids, max_iter):
        k = init_centroids.shape[0]

        def reduce_partials(sums, counts):
            return collectives.all_reduce_sum_chunked((sums, counts), axis)

        def update(centroids, sums, counts):
            return jnp.where(
                counts[:, None] > 0,
                sums / jnp.maximum(counts[:, None], 1e-30),
                centroids,
            )

        def cond(state):
            return state[3] < max_iter

        def step(state):
            centroids, local_sums, local_counts, epoch = state
            # epoch e-1's partials reduce here, overlapping this epoch's
            # distance matmul on hardware; epoch 0 reduces the zero init
            # (counts 0 -> centroids keep their init values, exactly the
            # eager loop's first assignment)
            sums, counts = reduce_partials(local_sums, local_counts)
            centroids = update(centroids, sums, counts)
            dists = measure.pairwise(X, centroids)
            assign = jnp.argmin(dists, axis=1)
            one_hot = jax.nn.one_hot(assign, k, dtype=X.dtype) * weights[:, None]
            # reduce-form segment sum, matching kmeans._lloyd_train_impl
            # (vmap-batching bit-stability — see ops/losses.py docstring)
            sums = jnp.sum(one_hot[:, :, None] * X[:, None, :], axis=0)
            return (centroids, sums, jnp.sum(one_hot, axis=0), epoch + 1)

        init = (
            init_centroids,
            jnp.zeros_like(init_centroids),
            jnp.zeros((k,), X.dtype),
            jnp.asarray(0, jnp.int32),
        )
        centroids, local_sums, local_counts, _ = lax.while_loop(cond, step, init)
        sums, counts = reduce_partials(local_sums, local_counts)
        return update(centroids, sums, counts), counts

    mapped = collectives.shard_map_over(
        mesh, (P(axis, None), P(axis), P(), P()), (P(), P()), fn=train
    )
    # tpulint: disable=retrace-hazard -- overlap mode builds one program per fit by design (opt-in; caching keyed on mesh/shape is ROADMAP item 2)
    return jax.jit(mapped)


def fleet_overlap_supported() -> bool:
    """Whether fleet training (fleet.py) can ride the overlap-scheduled
    shard_map programs. Currently False: the overlap programs are built
    per-mesh-shard with `shard_map`, and vmapping a shard_map body over a
    fleet axis would batch the deferred-reduction carry — the exact
    cross-epoch pipelining the scheme relies on — per member, which XLA
    re-serializes. A FitFleet therefore always trains on the plain
    vmapped resident kernels and counts the downgrade under
    `dispatch.whole_fit_fallback.fleet_overlap` so an overlap-tuned
    deployment notices fleet fits leaving the overlap path."""
    return False
