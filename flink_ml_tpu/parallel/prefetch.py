"""Input staging — accounted H2D uploads, shape bucketing, async prefetch.

The round-5 story for the *output* side of the tunnel (every readback is a
counted `readback.*` event riding the packed funnels) applied to the
*input* side. Three pieces, shared by every training loop and the serving
runner:

1. **Accounted staging** — `stage_to_device` / `stage_from_callback` are
   the ONLY sanctioned host→device transfer calls in `models/` and `ops/`
   (`scripts/check_upload_accounting.py` fails the build on a raw
   `jax.device_put` there, the mirror of the collective-accounting gate).
   Every upload increments `h2d.bytes` / `h2d.count`, so the BENCH
   metrics delta answers "how many bytes crossed the tunnel host→device"
   as exhaustively as it answers the readback question. Device→device
   re-placements transfer nothing and are not counted.

2. **Batch-shape bucketing** — `next_bucket` / `pad_rows`, the serving
   runner's recompile-bounding shape schedule (powers of two, pad =
   repeat the last REAL row — guard-safe by construction) promoted to a
   shared helper so the stream-training staging paths use the identical
   policy. Training paths pair the padding with weight-0 masking, which
   keeps bucketing bit-exact: a repeated row at weight 0 contributes
   +0.0 to every loss/gradient/count reduction.

3. **Double-buffered prefetch** — `Prefetcher` runs a caller-supplied
   `stage` function in ONE worker thread, up to `config.
   input_prefetch_depth` items ahead of consumption, yielding results in
   input order (a single worker keeps native-cache access serial, the
   constraint the hand-rolled loops in `ops/optimizer.py` and the KMeans
   stream fit enforced separately before this module replaced them).
   Batch b+1's cache read + pack + H2D upload ride under batch b's
   compute — the overlap the reference gets from DataCacheReader on
   Flink's async mailbox. Since the flow-control sweep the window is a
   `flow.BoundedChannel` (credit-based backpressure, per-consumer
   overload policies — the online estimators run their ingest through
   the same class with `shed_oldest`/`sample`), the worker is spawned by
   `flow.pump` (a worker error closes the channel with the error, so it
   re-raises at the consumer instead of silently stalling it), and every
   stage execution is timed by a `flow.StragglerWatchdog`.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from .. import flow
from ..obs import memledger, timeline
from ..utils import metrics

__all__ = [
    "stage_to_device",
    "stage_from_callback",
    "next_bucket",
    "pad_rows",
    "slice_rows",
    "Prefetcher",
]


# ---------------------------------------------------------------------------
# accounted H2D staging
# ---------------------------------------------------------------------------

def _host_nbytes(tree) -> int:
    """Bytes that will actually cross host→device: numpy leaves only —
    already-device-resident (jax) leaves re-place without a host upload."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, np.ndarray):
            total += leaf.nbytes
        elif not isinstance(leaf, jax.Array) and hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


def _admit_nbytes(tree, sharding) -> int:
    """Bytes the staging will make RESIDENT on one device — what budget
    admission must check, as distinct from `_host_nbytes` (bytes crossing
    the tunnel). With a sharding that splits the arrays, each device
    receives only its shard: a model-axis-sharded (d,) carry admits d/nm
    bytes against the per-device `config.hbm_budget_bytes`, which is
    exactly how the 2D mesh trains models whose replicated staging is
    rejected. No sharding (or a replicated one) admits the full bytes —
    identical to the pre-2D behaviour."""
    if sharding is None or not hasattr(sharding, "shard_shape"):
        return _host_nbytes(tree)
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            continue  # already resident: re-placement, not new residency
        nbytes = int(getattr(leaf, "nbytes", 0))
        shape = tuple(getattr(leaf, "shape", ()))
        size = 1
        for s in shape:
            size *= int(s)
        try:
            shard_shape = sharding.shard_shape(shape)
        except (TypeError, ValueError):
            total += nbytes
            continue
        ssize = 1
        for s in shard_shape:
            ssize *= int(s)
        total += (nbytes * ssize) // size if size > 0 else nbytes
    return total


def account_h2d(nbytes: int, arrays: int = 1, seconds: Optional[float] = None) -> None:
    """Fold one host→device transfer into the registry — the upload-side
    sibling of `obs.tracing.account_readback`. When the caller measured
    the staging call (`seconds`), the transfer also lands on the
    timeline's `h2d` lane (on an async backend that duration is the
    submit cost, not the wire time)."""
    import time

    metrics.inc_counter("h2d.count", arrays)
    metrics.inc_counter("h2d.bytes", int(nbytes))
    if timeline.enabled():
        dur_ns = int((seconds or 0.0) * 1e9)
        timeline.record_complete(
            timeline.LANE_H2D,
            "h2d",
            time.perf_counter_ns() - dur_ns,
            dur_ns,
            bytes=int(nbytes),
            arrays=arrays,
        )


def stage_to_device(tree, sharding=None, category: Optional[str] = None):
    """Accounted `jax.device_put`: upload a host array (or pytree of
    arrays; dtypes canonicalize exactly as `device_put` does) and count
    the host bytes moved. The one H2D funnel `models/` and `ops/` are
    allowed to call (see `scripts/check_upload_accounting.py`).

    Every call is budget-admitted against `config.hbm_budget_bytes`
    (typed `HbmBudgetExceeded` BEFORE the allocating dispatch) and a
    real backend OOM is re-raised as `HbmExhausted` with the ranked
    ledger snapshot. `category` additionally ledgers the staged arrays'
    *residency* (obs/memledger.py) — declare it for long-lived uploads
    (model constants, the optimizer carry, stacked whole-fit segments,
    serving batches); leave it None for transients and for batches the
    DeviceEpochCache will own (the cache does its own exact
    register/release accounting, so a category here would double
    count)."""
    import time

    import jax

    nbytes = _host_nbytes(tree)
    memledger.admit(_admit_nbytes(tree, sharding), category)
    t0 = time.perf_counter()
    try:
        if sharding is not None:
            out = jax.device_put(tree, sharding)
        else:
            out = jax.device_put(tree)
    except Exception as e:
        wrapped = memledger.wrap_oom(e)
        if wrapped is not None:
            raise wrapped from e
        raise
    if nbytes:
        account_h2d(nbytes, seconds=time.perf_counter() - t0)
    if category is not None:
        memledger.track(out, category)
    return out


def stage_from_callback(shape, sharding, data_callback, category: Optional[str] = None):
    """Accounted `jax.make_array_from_callback` (the per-shard zero-copy
    staging path of `_batchify`); bytes are counted from the staged
    array's own dtype, so callers need not precompute it. Budget
    admission, OOM wrapping and optional residency tracking exactly as
    `stage_to_device` (the byte estimate for admission uses the shape's
    float32 size when the dtype is only known post-staging)."""
    import time

    import jax

    admit_shape = tuple(shape)
    if hasattr(sharding, "shard_shape"):
        try:
            admit_shape = sharding.shard_shape(tuple(shape))
        except (TypeError, ValueError):
            pass
    memledger.admit(int(np.prod(admit_shape)) * 4, category)
    t0 = time.perf_counter()
    try:
        out = jax.make_array_from_callback(tuple(shape), sharding, data_callback)
    except Exception as e:
        wrapped = memledger.wrap_oom(e)
        if wrapped is not None:
            raise wrapped from e
        raise
    account_h2d(
        int(np.prod(shape)) * out.dtype.itemsize, seconds=time.perf_counter() - t0
    )
    if category is not None:
        memledger.track(out, category)
    return out


# ---------------------------------------------------------------------------
# batch-shape bucketing (shared with serving.MicroBatchServer)
# ---------------------------------------------------------------------------

def next_bucket(n: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest bucket >= n. Default schedule: powers of two (>= 8), the
    classic recompile-bounding shape schedule; an explicit sorted bucket
    list wins when the traffic distribution is known."""
    if n <= 0:
        return n  # empty batch: nothing to pad
    if buckets:
        for b in buckets:
            if b >= n:
                return int(b)
        return int(n)  # beyond the largest bucket: exact shape
    b = 8
    while b < n:
        b <<= 1
    return b


def pad_rows(col, n: int, bucket: int):
    """Pad a column from n to bucket rows by repeating its final row (a
    real row: guard-safe — a copy of real data can never fire a
    validation guard the real data would not). Works for host numpy,
    device arrays and SparseBatch; training callers mask the padding
    with weight 0, which keeps the pad bit-invisible to every reduction."""
    if bucket == n:
        return col
    from ..table import SparseBatch

    if isinstance(col, SparseBatch):
        return SparseBatch(
            col.size,
            pad_rows(col.indices, n, bucket),
            pad_rows(col.values, n, bucket),
        )
    try:
        import jax

        if isinstance(col, jax.Array):
            import jax.numpy as jnp

            reps = jnp.broadcast_to(col[n - 1 :], (bucket - n,) + col.shape[1:])
            return jnp.concatenate([col, reps])
    except ImportError:  # pragma: no cover
        pass
    col = np.asarray(col)
    reps = np.broadcast_to(col[n - 1 :], (bucket - n,) + col.shape[1:])
    return np.concatenate([col, reps])


def slice_rows(col, n: int):
    """Undo `pad_rows` on an output column (device slice, no host pull)."""
    from ..table import SparseBatch

    if isinstance(col, SparseBatch):
        return SparseBatch(col.size, col.indices[:n], col.values[:n])
    return col[:n]


# ---------------------------------------------------------------------------
# bounded-depth single-worker prefetch
# ---------------------------------------------------------------------------

class Prefetcher:
    """Run `stage(item)` in one worker thread up to `depth` items ahead.

    The staging window is a `flow.BoundedChannel`: with the default
    `block` policy, `iterate(items)` yields staged results strictly in
    input order — no drops, no reordering, whatever the relative speed of
    producer and consumer (credit-based backpressure: the worker stalls
    once `depth` items wait unconsumed). The online estimators pass
    `policy="shed_oldest"`/`"sample"` for bounded-memory, tracked-
    staleness ingest instead (see docs/flow_control.md). The worker is
    created per iteration and torn down when the generator closes
    (including early exits: a training loop that stops on tol simply
    abandons the generator and the speculative staging work is
    cancelled). An exception raised inside `stage` — or by the source
    iterable — surfaces to the consuming iterator, re-raised at the next
    `__next__` after the items staged before it; a dead worker can never
    silently stall the consumer. `depth` defaults to
    `config.input_prefetch_depth`.
    """

    def __init__(
        self,
        stage: Callable[[Any], Any],
        depth: Optional[int] = None,
        policy: str = flow.BLOCK,
        name: str = "prefetch",
    ):
        from .. import config

        self.stage = stage
        self.depth = max(1, int(depth if depth is not None else config.input_prefetch_depth))
        self.policy = policy
        self.name = name
        self.watchdog = flow.StragglerWatchdog(name)
        self.channel: Optional[flow.BoundedChannel] = None  # latest iterate()'s window

    def iterate(self, items: Iterable) -> Iterator:
        metrics.set_gauge("prefetch.depth", self.depth)
        channel = flow.BoundedChannel(self.depth, policy=self.policy, name=self.name)
        self.channel = channel
        flow.pump(items, channel, transform=self.stage, watchdog=self.watchdog)
        try:
            yield from channel
        finally:
            channel.cancel()  # early exit: stop the speculative staging
