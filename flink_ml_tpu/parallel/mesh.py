"""Device-mesh construction and sharding helpers.

The reference scales by Flink task parallelism with netty shuffles between
subtasks (SURVEY.md §2.3 parallelism table). The TPU-native analogue is a
`jax.sharding.Mesh` over the chip topology: the `data` axis carries data
parallelism (the reference's rebalance()+allReduceSum), the optional
`model` axis feature-shards wide linear models (the TP analogue for sparse
high-dim LR). Collectives ride ICI; multi-host extends the same mesh over
DCN via `jax.distributed.initialize` (see `init_distributed`).
"""

from __future__ import annotations

import math
from contextlib import contextmanager
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_default_mesh: Optional[Mesh] = None


def create_mesh(
    axis_names: Sequence[str] = (DATA_AXIS,),
    shape: Optional[Sequence[int]] = None,
    devices=None,
) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    If `shape` is omitted, all devices go on the first axis and the rest get
    size 1. Uses jax's device order, which follows the ICI topology on TPU
    so neighbouring mesh coordinates are ICI neighbours.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    if math.prod(shape) != len(devices):
        raise ValueError(f"Mesh shape {shape} does not match {len(devices)} devices")
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def default_mesh() -> Mesh:
    """The process-wide default mesh: all devices on the `data` axis."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = create_mesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


@contextmanager
def use_mesh(mesh: Mesh):
    global _default_mesh
    prev = _default_mesh
    _default_mesh = mesh
    try:
        yield mesh
    finally:
        _default_mesh = prev


def create_mesh_2d(
    model_shards: int,
    devices=None,
    num_hosts: Optional[int] = None,
) -> Mesh:
    """Build the true 2D `(data, model)` training mesh: the device grid
    factorized as (device_count / model_shards) × model_shards with the
    MODEL axis innermost.

    Innermost-model is the layout that keeps the factorization host-group
    aware: `host_groups` (and real multi-host process boundaries) slice the
    flat device order into contiguous slabs, and with the model axis minor
    each slab owns WHOLE data-axis rows — a feature-axis all-gather stays
    inside one host's ICI domain while the data-axis gradient reduce is
    the only collective that crosses host slabs (the Snap ML hierarchy:
    TP inside the node, DP across nodes). With `num_hosts` the alignment
    is validated up front: every host slab must hold a multiple of
    `model_shards` devices, otherwise a data row straddles hosts and the
    cheap-axis/expensive-axis split silently inverts.
    """
    devices = list(devices if devices is not None else jax.devices())
    model_shards = int(model_shards)
    if model_shards < 1:
        raise ValueError(f"model_shards must be >= 1, got {model_shards}")
    if len(devices) % model_shards:
        raise ValueError(
            f"model_shards={model_shards} does not divide {len(devices)} devices"
        )
    if num_hosts is not None:
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        for h, group in enumerate(
            np.array_split(np.arange(len(devices)), num_hosts)
        ):
            if len(group) % model_shards:
                raise ValueError(
                    f"host {h} owns {len(group)} of {len(devices)} devices — "
                    f"not a multiple of model_shards={model_shards}; a "
                    "data-axis row would straddle hosts (re-factor the grid "
                    "or change the host count)"
                )
    return create_mesh(
        (DATA_AXIS, MODEL_AXIS),
        shape=(len(devices) // model_shards, model_shards),
        devices=devices,
    )


def num_data_shards(mesh: Mesh) -> int:
    return int(mesh.shape.get(DATA_AXIS, 1))


def num_model_shards(mesh: Mesh) -> int:
    return int(mesh.shape.get(MODEL_AXIS, 1))


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard leading (batch) dim over the data axis, replicate the rest —
    the layout of training examples."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def model_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the trailing (feature) dim over the model axis — the layout of
    feature-sharded wide model vectors."""
    if MODEL_AXIS not in mesh.axis_names:
        return replicated_sharding(mesh)
    return NamedSharding(mesh, P(*([None] * (ndim - 1)), MODEL_AXIS))


def data_model_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """The 2D training layout for rank >= 2 operands: leading (batch) dim
    over `data`, trailing (feature) dim over `model`, middle dims
    replicated — batches split across data shards while each data row's
    feature slice splits across the model axis. Falls back to the plain
    data layout when the mesh has no model axis."""
    if ndim < 2:
        raise ValueError(
            f"data_model_sharding needs ndim >= 2 (got {ndim}); rank-1 "
            "operands are either data_sharding or model_sharding"
        )
    if MODEL_AXIS not in mesh.axis_names:
        return data_sharding(mesh, ndim)
    return NamedSharding(
        mesh, P(DATA_AXIS, *([None] * (ndim - 2)), MODEL_AXIS)
    )


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — the analogue of the reference's broadcast variables
    (BroadcastUtils.withBroadcastStream, BroadcastUtils.java:64)."""
    return NamedSharding(mesh, P())


def fleet_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard the leading FLEET axis of per-member state ([N, ...] carries,
    [N, pack] hypers/results) over the mesh data axis.

    The fleet-sharded regime (fleet.py) inverts the usual layout: when
    N x per-member state exceeds one device, the fleet axis rides the
    `data` mesh axis — each device owns N/shards whole members — and the
    training DATA is replicated instead (each member still sees every
    example, so member math is untouched and solo-fit bit-parity holds).
    The spec is identical to `data_sharding`; the distinct helper exists
    because the two axes mean different things: a reduce over `data` in
    the fleet regime would SUM ACROSS MEMBERS, which no fleet kernel may
    ever emit."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def fleet_axis_shardable(mesh: Mesh, fleet_size: int) -> bool:
    """Whether a fleet of `fleet_size` members can shard its member axis
    over this mesh's data axis: the axis must exist with >1 shards and
    divide the fleet evenly (ragged member shards would force padded
    members whose dead lanes still burn flops in every vmapped epoch)."""
    shards = num_data_shards(mesh)
    return shards > 1 and fleet_size % shards == 0


def pad_to_multiple(array, multiple: int, axis: int = 0, pad_value=0):
    """Pad `axis` up to a multiple so it divides evenly across shards.

    TPUs need static, evenly divisible shapes; the reference instead lets
    Flink deal ragged partitions. Returns (padded, original_length).
    """
    n = array.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return array, n
    pad_width = [(0, 0)] * array.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(np.asarray(array), pad_width, constant_values=pad_value), n


def shard_batch(mesh: Mesh, array, pad_value=0) -> Tuple[jax.Array, int]:
    """Device-put a host array sharded over the data axis (padding as needed).

    Returns (device_array, original_row_count). The padding rows must be
    masked out by the caller (weight 0 in training math).
    """
    shards = num_data_shards(mesh)
    padded, n = pad_to_multiple(np.asarray(array), shards, axis=0, pad_value=pad_value)
    return jax.device_put(padded, data_sharding(mesh, padded.ndim)), n


def replicate(mesh: Mesh, array) -> jax.Array:
    return jax.device_put(np.asarray(array), replicated_sharding(mesh))


# ---------------------------------------------------------------------------
# host-group mapping (multi-host snapshot coordination, ckpt/coordinator.py)
# ---------------------------------------------------------------------------
# On real DCN hardware `jax.devices()` spans processes and each host owns a
# contiguous slab of the device order (jax's device order follows the ICI
# topology, and process boundaries align with it). The virtual-device
# substrate models the same shape: a "host" is a contiguous group of mesh
# devices, and a leaf's per-host shard is the slice of the FULL array that
# host's devices would hold under the leaf's sharding tag. The tag->axis
# mapping lives here, next to the `<tag>_sharding` constructors it mirrors:
# `data` shards the leading (batch) dim, `model` the trailing (feature)
# dim, `replicated`/`host` leaves are whole-array and owned by host 0.

def host_groups(mesh: Mesh, num_hosts: int):
    """The mesh's devices as `num_hosts` contiguous groups (host i owns
    group i). Host counts need not divide the device count — trailing
    groups may be one device short (np.array_split semantics), and a host
    count above the device count leaves the surplus hosts empty-handed
    for devices but still shard OWNERS for snapshot writes."""
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    devices = list(mesh.devices.flat)
    return [list(g) for g in np.array_split(np.array(devices), num_hosts)]


def form_mesh_over(groups: Sequence[Sequence], axis_names: Sequence[str] = (DATA_AXIS,)) -> Mesh:
    """Re-form a mesh over the concatenation of the given host device
    groups — the survivor mesh after the elastic supervisor
    (parallel/supervisor.py) quarantines a failed host. Groups come from
    `host_groups`; empty groups (surplus hosts) contribute nothing."""
    devices = [d for g in groups for d in g]
    if not devices:
        raise ValueError("cannot form a mesh over zero surviving devices")
    return create_mesh(axis_names, devices=devices)


def shard_axis_for_tag(tag: str, ndim: int) -> Optional[int]:
    """The array axis a sharding-spec tag splits across hosts, or None for
    whole-array tags (`replicated` / `host`). Mirrors `data_sharding`
    (leading dim) and `model_sharding` (trailing dim)."""
    if ndim <= 0:
        return None
    if tag == "data":
        return 0
    if tag == "model":
        return ndim - 1
    return None


def host_slice_bounds(length: int, num_hosts: int):
    """Per-host [start, stop) bounds splitting `length` rows/cols across
    `num_hosts` (np.array_split semantics: uneven lengths allowed, empty
    trailing slices when hosts outnumber elements)."""
    if num_hosts < 1:
        raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
    base, extra = divmod(int(length), int(num_hosts))
    bounds = []
    start = 0
    for h in range(num_hosts):
        stop = start + base + (1 if h < extra else 0)
        bounds.append((start, stop))
        start = stop
    return bounds


def init_distributed(coordinator_address: Optional[str] = None, **kwargs) -> None:
    """Multi-host bring-up over DCN (the analogue of the reference's cluster
    deployment). No-op when running single-process."""
    if coordinator_address is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address, **kwargs)
