"""Dispatch pipeline — chunked epoch programs with bounded-depth async drains.

The round-5 trace named the wall: a warm LogisticRegression fit is 2.6 ms
busy on device out of ~125 ms wall; the rest is the remote tunnel's fixed
dispatch+readback latency, paid once per host↔device synchronization. The
reference hides the same cost with epoch watermarks + chunked all-reduce
batching (its per-epoch progress is batched through the feedback channel,
not round-tripped through the driver). The TPU-native equivalent here has
two parts:

1. **Epoch chunking** — `chunk_runner(body)` compiles `body` into a
   program that advances up to K epochs in one `lax.while_loop`, reading
   back ONE packed (epoch, criteria) scalar pair per chunk instead of one
   criteria scalar per epoch. The tol check runs *inside* the chunk at
   every epoch, in the same order as the unchunked host loop, so the stop
   epoch and the final carry are bit-identical for any K.

2. **Bounded-depth speculation** — because a chunk whose entry criteria
   already satisfies tol is an identity function (the while condition is
   false on entry), chunks can be dispatched ahead of their predecessors'
   convergence readbacks without changing semantics. `DrainQueue` holds up
   to `config.iteration_dispatch_depth` dispatched chunks whose packed
   scalars have not been read back; host Python overlaps device execution
   instead of serializing on every chunk.

Carry donation: the chunk programs ping-pong the carry in place in HBM
(`donate_argnums`) when the backend supports buffer donation and the
caller does not need to retain the pre-chunk carry (checkpoint boundaries
and listener callbacks retain; everything else donates).

Every blocking drain is accounted as `iteration.host_sync` (obs/tracing),
so BENCH deltas surface dispatch regressions.

Drain boundaries are also the job-checkpoint hook points: a drained chunk
whose end lands on a checkpoint boundary (`next_boundary` clamps chunk
ends so it always does) has its retained carry snapshotted through the
JobSnapshot API (flink_ml_tpu/ckpt/snapshot.py) by the drain handlers in
`parallel/iteration.py` and `ops/optimizer.py`, and the fault-injection
`chunk` site ticks once per drained entry (docs/fault_tolerance.md).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..obs import hist, timeline, tracing
from ..utils import metrics


def supports_donation() -> bool:
    """Buffer donation is a no-op (with a warning) on the CPU backend."""
    import jax

    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


# ---------------------------------------------------------------------------
# whole-fit resident programs: eligibility + accounting
# ---------------------------------------------------------------------------
#
# Under `config.whole_fit == "auto"` the training loops compile the ENTIRE
# fit — epoch loop to maxIter, per-epoch convergence check, final model
# update, and the packed result — into one resident device program, so a
# fit is exactly ONE dispatch and ONE packed readback regardless of the
# chunk knobs. The compile key is the (shape-bucket x packed-hyperparam
# layout): data shapes and the loss are jit static structure, while the
# packed f32 hyper vector, maxIter, tol, and the carry are runtime
# operands — repeated fits at one shape bucket re-enter one executable.
# `whole_fit_plan` is the central eligibility decision; a fit that cannot
# be resident falls back to the chunked DrainQueue path below, counted per
# reason (docs/performance.md "Whole-fit resident programs").

#: The fallback-reason label set (`dispatch.whole_fit_fallback.<reason>`):
#: - checkpoint_interval: a snapshot boundary lands strictly inside the
#:   fit — the chunked path must surface the carry at that epoch.
#: - device_cache_budget: the stacked stream data source does not fit the
#:   `config.device_cache_bytes` HBM budget (or the cache is disabled).
#: - ragged_batches: stream batches bucket to different row counts, so no
#:   single stacked (nb, rows, cols) array exists to index in-program.
#: - listener: a per-epoch listener needs every (epoch, carry) pair on
#:   the host — resident programs have no per-epoch host boundary.
WHOLE_FIT_FALLBACK_REASONS = (
    "checkpoint_interval",
    "device_cache_budget",
    "ragged_batches",
    "listener",
)


def whole_fit_enabled() -> bool:
    """Is the whole-fit resident-program mode on (`config.whole_fit`)?"""
    from .. import config

    return config.whole_fit == "auto"


def account_whole_fit(kind: str = "fit") -> None:
    """Count a fit taking the resident-program path (`dispatch.whole_fit`
    + a per-loop kind: sgd / stream / lloyd / iterate / fleet — `fleet`
    counts ONE for the whole N-member vmapped program, which is the
    point: `fleet.modelsTrained` / `dispatch.whole_fit.fleet` is the
    amortization ratio)."""
    metrics.inc_counter("dispatch.whole_fit")
    metrics.inc_counter(f"dispatch.whole_fit.{kind}")


def account_whole_fit_fallback(reason: str) -> None:
    """Count a whole-fit-eligible loop falling back to the chunked path,
    labelled with WHY (`dispatch.whole_fit_fallback.<reason>`) — the BENCH
    runner surfaces the totals, so a config change that silently knocks
    fits off the resident path shows up as a counter jump."""
    metrics.inc_counter("dispatch.whole_fit_fallback")
    metrics.inc_counter(f"dispatch.whole_fit_fallback.{reason}")
    if timeline.enabled():
        timeline.record_instant(
            timeline.LANE_DISPATCH, "whole_fit.fallback", reason=reason
        )


def whole_fit_plan(
    *,
    start_epoch: int,
    max_iter: int,
    checkpoint_interval: Optional[int] = None,
    data_bytes: Optional[int] = None,
    uniform_batches: bool = True,
    listener: bool = False,
) -> Tuple[bool, Optional[str]]:
    """The central whole-fit eligibility decision: (take, fallback_reason).

    `checkpoint_interval` is the snapshot cadence when checkpointing is
    active (None = no checkpointing): a boundary strictly inside
    (start_epoch, max_iter) forces the chunked path; a boundary AT fit end
    stays whole-fit — the loop snapshots once after its single readback.
    `data_bytes` is the stacked stream data source's size, checked against
    the device-cache budget. Returns (False, None) with NO fallback count
    when the mode is off — fallbacks are only meaningful for fits that
    asked to be resident."""
    if not whole_fit_enabled():
        return False, None
    reason = None
    if listener:
        reason = "listener"
    if reason is None and checkpoint_interval is not None:
        boundary = next_boundary(start_epoch, checkpoint_interval)
        if boundary is not None and boundary < max_iter:
            reason = "checkpoint_interval"
    if reason is None and not uniform_batches:
        reason = "ragged_batches"
    if reason is None and data_bytes is not None:
        from ..data.devicecache import within_device_budget

        if not within_device_budget(data_bytes):
            reason = "device_cache_budget"
    if reason is not None:
        account_whole_fit_fallback(reason)
        return False, reason
    return True, None


# ---------------------------------------------------------------------------
# chunk runner: K epochs of `body` as one program
# ---------------------------------------------------------------------------

class ChunkRunner(NamedTuple):
    """Jitted chunk steppers for one body function.

    Both advance `(carry, epoch, criteria)` to `min(chunk_end, tol-fire)`
    and additionally return a packed f32 [epoch, criteria] pair for a
    single-transfer drain. `donating` consumes the input state buffers
    (in-place HBM ping-pong); `borrowing` leaves them valid — use it when
    the pre-chunk carry must stay readable (checkpoint snapshot pending,
    listener holding a reference) or on backends without donation.
    """

    donating: Callable
    borrowing: Callable


_runner_cache: Dict[Any, ChunkRunner] = {}


def chunk_runner(body) -> ChunkRunner:
    """Build (or fetch) the chunk steppers for `body(carry, epoch) ->
    (carry, criteria)`. Cached per body object so repeated loops over the
    same body reuse the compiled executables."""
    cached = _runner_cache.get(body)
    if cached is not None:
        return cached

    import jax
    import jax.numpy as jnp
    from jax import lax

    def chunk_step(carry, epoch, criteria, chunk_end, tol_value):
        def cond(state):
            _, e, crit = state
            return jnp.logical_and(e < chunk_end, crit > tol_value)

        def step(state):
            c, e, _ = state
            new_c, crit = body(c, e)
            return new_c, e + 1, jnp.asarray(crit, jnp.float32)

        carry, epoch, criteria = lax.while_loop(
            cond, step, (carry, epoch, criteria)
        )
        packed = jnp.stack([epoch.astype(jnp.float32), criteria])
        return carry, epoch, criteria, packed

    runner = ChunkRunner(
        # tpulint: disable=retrace-hazard -- wrapper pair cached per body object in _runner_cache (keyed on `body`)
        donating=jax.jit(chunk_step, donate_argnums=(0, 1, 2)),
        # tpulint: disable=retrace-hazard -- wrapper pair cached per body object in _runner_cache (keyed on `body`)
        borrowing=jax.jit(chunk_step),
    )
    _runner_cache[body] = runner
    return runner


def clear_runner_cache() -> None:
    _runner_cache.clear()


def timed_dispatch(step: Callable, *args, start: int = None, end: int = None):
    """THE accounted chunk-dispatch funnel: every chunk program launch in
    the iteration runtime rides through here, so the host-side dispatch
    cost is one timer (`iteration.dispatch` — the `hostDispatchMs` BENCH
    field) and one timeline `dispatch`-lane event, and the dispatch-wall
    attribution (`obs.timeline.dispatch_attribution`) can split every
    fit's wall into dispatch + device + readback + idle-gap. On an async
    backend this times the enqueue; on CPU, the synchronous execution —
    either way it is exactly the time the host thread was captive to the
    launch. `start`/`end` are the chunk's planned epoch range (drives the
    per-epoch attribution). Under a supervised fit
    (parallel/supervisor.py) every launch is also a host-health
    boundary: the supervisor's `host.die`/`host.hang` fault sites tick
    here (the mid-epoch chaos axis) and the launch duration feeds the
    hang watchdog's chunk-wall EMA."""
    from . import supervisor

    supervisor.pulse_boundary(supervisor.PHASE_DISPATCH)
    t0 = time.perf_counter_ns()
    try:
        out = step(*args)
    except Exception as e:
        # a backend RESOURCE_EXHAUSTED surfacing from the launch becomes
        # the typed HbmExhausted carrying the ranked ledger snapshot —
        # the OOM names who holds the memory, not just that it ran out
        from ..obs import memledger

        wrapped = memledger.wrap_oom(e)
        if wrapped is not None:
            raise wrapped from e
        raise
    dur_ns = time.perf_counter_ns() - t0
    metrics.record_time("iteration.dispatch", dur_ns / 1e9)
    supervisor.note_progress(dur_ns / 1e9)
    if timeline.enabled():
        attrs = {}
        if start is not None:
            attrs["start"] = int(start)
        if end is not None:
            attrs["end"] = int(end)
        timeline.record_complete(
            timeline.LANE_DISPATCH, "dispatch.chunk", t0, dur_ns, **attrs
        )
    return out


# ---------------------------------------------------------------------------
# bounded-depth drain queue
# ---------------------------------------------------------------------------

class InFlight(NamedTuple):
    """One dispatched, undrained chunk."""

    start: int  # planned first epoch of the chunk (speculative frontier)
    end: int  # planned past-the-end epoch
    carry: Any  # device carry AFTER the chunk (None when not retained)
    packed: Any  # device f32 [epoch, criteria]


class DrainQueue:
    """Bounded-depth queue of dispatched chunks awaiting their convergence
    readback. `push` drains the oldest entry once more than `depth` chunks
    are in flight; `drain_all` empties it. Every drain is one blocking
    packed-scalar readback, accounted as `iteration.host_sync`."""

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        # tpulint: disable=unbounded-queue -- depth-bounded by construction: push() drains past self.depth in the same call, single-threaded
        self._q: deque = deque()
        tracing.set_dispatch_depth(self.depth)

    def __len__(self) -> int:
        return len(self._q)

    def push(self, entry: InFlight) -> List[Tuple[InFlight, int, float]]:
        """Queue a dispatched chunk; returns the drained (entry, epoch,
        criteria) records (empty while the queue is under its depth)."""
        self._q.append((entry, time.perf_counter_ns()))
        if timeline.enabled():  # the dispatch window is a flow channel too
            timeline.record_instant(
                timeline.LANE_FLOW, "drainqueue.push", depth=len(self._q)
            )
        drained = []
        while len(self._q) > self.depth:
            drained.append(self._drain_one())
        return drained

    def drain_all(self) -> List[Tuple[InFlight, int, float]]:
        out = []
        while self._q:
            out.append(self._drain_one())
        return out

    def _drain_one(self) -> Tuple[InFlight, int, float]:
        import jax

        from . import supervisor

        entry, pushed_ns = self._q.popleft()
        # the blocking readback is where a wedged collective manifests —
        # the supervised mid-collective boundary sits right before it
        supervisor.pulse_boundary(supervisor.PHASE_COLLECTIVE)
        t0_ns = time.perf_counter_ns()
        t0 = time.perf_counter()
        host = np.asarray(jax.device_get(entry.packed))
        tracing.account_host_sync("drain")
        tracing.account_readback(host.nbytes, time.perf_counter() - t0)
        end_ns = time.perf_counter_ns()
        # chunk wall: dispatch push -> drained scalar on host, the
        # per-chunk latency distribution of the dispatch pipeline — and
        # the hang watchdog's EMA sample under a supervised fit
        hist.record("iteration.chunkWallMs", (end_ns - pushed_ns) / 1e6)
        supervisor.note_progress((end_ns - pushed_ns) / 1e9)
        if timeline.enabled():
            # estimated device-execution interval: dispatch end to the
            # blocking readback start (exact on a synchronous backend,
            # an upper bound under async dispatch — the drain may also
            # have waited on still-running device work)
            timeline.record_complete(
                timeline.LANE_DEVICE,
                "device.chunk(est)",
                pushed_ns,
                max(0, t0_ns - pushed_ns),
                start=entry.start,
                end=entry.end,
            )
        return entry, int(host[0]), float(host[1])


def drain_packed(packed) -> Tuple[int, float]:
    """Blocking readback of one packed [epoch, criteria] pair (the
    depth-1 / tail path), with the same accounting as DrainQueue."""
    import jax

    from . import supervisor

    supervisor.pulse_boundary(supervisor.PHASE_COLLECTIVE)
    t0 = time.perf_counter()
    host = np.asarray(jax.device_get(packed))
    tracing.account_host_sync("drain")
    tracing.account_readback(host.nbytes, time.perf_counter() - t0)
    supervisor.note_progress(time.perf_counter() - t0)
    return int(host[0]), float(host[1])


def next_boundary(epoch: int, interval: Optional[int]) -> Optional[int]:
    """The first checkpoint boundary strictly after `epoch` (None without
    checkpointing). Chunk ends clamp to boundaries so snapshots keep their
    exact epoch cadence under chunking."""
    if not interval or interval <= 0:
        return None
    return (epoch // interval + 1) * interval
