"""Elastic training supervisor — live host-failure detection, collective
hang watchdog, automatic shrink-and-resume.

PR 14 built the storage half of elasticity: committed sharded snapshot
cuts that re-stitch onto any host count (`ckpt/coordinator.py`). Nothing
*used* it at runtime — a host that dies or hangs mid-fit leaves every
surviving shard blocked inside a collective forever, and recovery is a
human re-running the fit. The Spark-performance study (PAPERS.md) measures
exactly this: failed/straggling workers, not steady-state throughput,
dominate tail training time. This module closes the loop: any checkpointed
fit (SGD chunked/stream, out-of-core KMeans, `iterate_bounded`) runs under
a host-health protocol, and a detected failure triggers quarantine →
mesh re-form over survivors → elastic restore of the newest committed
cut → automatic resume, bounded by `config.recovery_budget`.

The protocol has two INDEPENDENT detectors, because the two failure
modes have disjoint observable signatures:

- **Heartbeats → `HostFailure`.** Each (simulated) host — a contiguous
  mesh device group, `mesh.host_groups` — owns a heartbeat on the
  supervisor's side channel (the DCN-heartbeat analogue: a per-host
  sender thread in a real deployment; on the virtual substrate the
  monitor animates the senders of live hosts each poll). A host whose
  beat is older than `config.host_heartbeat_timeout_s` is dead. A dead
  host CANNOT be seen by the hang watchdog alone: its peers may still be
  dispatching for a while, and conversely —
- **Progress deadline → `CollectiveHang`.** A host that is alive but
  stuck (wedged collective, stuck commit) keeps heartbeating, so the
  heartbeat detector stays green; what stops is *progress*. Every chunk
  dispatch (`dispatch.timed_dispatch`), drain (`DrainQueue`) and
  snapshot-commit step pulses the supervisor; the deadline is
  `config.hang_factor` × the EMA of the chunk wall
  (`flow.StragglerWatchdog`'s trailing mean — reused here, but escalated
  to a typed failure instead of a counter), floored at
  `config.hang_min_deadline_s` so fast warm chunks don't turn scheduler
  jitter into detections.

On detection the supervisor aborts the attempt: the abort event wakes
the fit thread (which unwinds with `SupervisorAbort`), the in-flight
snapshot cut is cancelled with `SnapshotAborted` semantics — partial
shard files swept, previous committed cut untouched
(`coordinator.sweep_uncommitted` plus the coordinator's own
exception-path sweep) — the failed host group is quarantined, the mesh
re-forms over the survivors (`mesh.form_mesh_over`), and the fit re-runs:
its own checkpoint machinery restores the newest committed cut
elastically onto the new mesh. A resume on the SAME host count is
bit-identical to an unkilled fit (the PR 6 contract); across host counts
it is allclose per the documented reduction-order caveat
(docs/fault_tolerance.md "Failure domains and automatic recovery").

Fault injection (`ckpt/faults.py`): the `host.die` / `host.hang` sites
tick at every supervised boundary, phase-qualified twins
(`host.die.dispatch` / `.collective` / `.commit`, same for hang) let the
chaos matrix target a kill mid-epoch, mid-collective or mid-commit. A
fired `host.die` stops the victim's heartbeat sender; a fired
`host.hang` (and every boundary after a death — survivors stuck in the
collective with a dead peer) blocks the fit thread until the supervisor
aborts. Detection therefore happens ONLY through the two observable
signals above — the injection harness never tells the monitor anything.

Obs: the `supervisor` timeline lane records detect/stall/recover
instants; `supervisor.detectionMs` / `supervisor.recoveryMs` gauges and
`supervisor.hostFailure` / `supervisor.collectiveHang` /
`supervisor.recovery` / `supervisor.quarantine` counters feed the
`elasticRecovery` BENCH entry.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import flow
from ..ckpt import faults
from ..obs import timeline
from ..utils import metrics
from . import mesh as mesh_lib

__all__ = [
    "HostFailure",
    "CollectiveHang",
    "SupervisorAbort",
    "RecoveryBudgetExhausted",
    "FailureEvent",
    "SupervisedResult",
    "HostBoard",
    "SupervisorContext",
    "supervise",
    "pulse_boundary",
    "note_progress",
    "active",
]

#: Boundary phases a supervised fit pulses through (the chaos-matrix axes).
PHASE_DISPATCH = "dispatch"  # a chunk program was launched (mid-epoch)
PHASE_COLLECTIVE = "collective"  # a blocking drain/readback (mid-collective)
PHASE_COMMIT = "commit"  # a snapshot shard/manifest write (mid-commit)


class HostFailure(RuntimeError):
    """A (simulated) host stopped heartbeating past
    `config.host_heartbeat_timeout_s`: the host is gone, its devices are
    quarantined, and the mesh must re-form without them."""

    def __init__(self, host: int, age_s: float, phase: Optional[str] = None):
        super().__init__(
            f"host {host} heartbeat is {age_s * 1000.0:.0f}ms old "
            f"(timeout exceeded){f' at the {phase} boundary' if phase else ''}"
        )
        self.host = host
        self.age_s = age_s
        self.phase = phase


class CollectiveHang(RuntimeError):
    """The supervised fit stopped making dispatch/drain/commit progress
    past the hang deadline while every host still heartbeats — the
    blocked-in-a-collective (or wedged-commit) failure mode. `host` is
    the last boundary's non-participant when the board observed one
    (collective-entry attribution), else None."""

    def __init__(
        self,
        elapsed_s: float,
        deadline_s: float,
        host: Optional[int] = None,
        phase: Optional[str] = None,
    ):
        super().__init__(
            f"no fit progress for {elapsed_s * 1000.0:.0f}ms "
            f"(hang deadline {deadline_s * 1000.0:.0f}ms)"
            + (f"; host {host} never entered the {phase or 'pending'} boundary"
               if host is not None else "")
        )
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s
        self.host = host
        self.phase = phase


class SupervisorAbort(RuntimeError):
    """Control-flow unwind of an aborted supervised attempt: raised out
    of the stalled boundary in the FIT thread once the supervisor's
    monitor decided the attempt is dead. Never escapes `supervise` —
    the worker reports it and the supervisor recovers or gives up."""

    def __init__(self, phase: str):
        super().__init__(f"supervised attempt aborted at the {phase} boundary")
        self.phase = phase


class RecoveryBudgetExhausted(RuntimeError):
    """More failures than `config.recovery_budget` recoveries: the
    supervisor gives up, carrying every typed failure it observed so the
    operator sees the whole history, not just the last symptom."""

    def __init__(self, events: Sequence["FailureEvent"]):
        kinds = ", ".join(f"{e.kind}@{e.phase or '?'}" for e in events)
        super().__init__(
            f"recovery budget exhausted after {len(events)} failures ({kinds})"
        )
        self.events = list(events)


@dataclass
class FailureEvent:
    """One detected failure and what recovery cost."""

    kind: str  # "hostFailure" | "collectiveHang"
    host: Optional[int]
    phase: Optional[str]  # boundary phase the fault surfaced at (if known)
    detection_ms: float  # fault observable -> monitor detected
    recovery_ms: Optional[float] = None  # detected -> resumed fit's 1st progress
    quarantined: bool = False
    hosts_after: int = 0


@dataclass
class SupervisedResult:
    """`supervise`'s return: the fit's value plus the failure ledger."""

    value: Any
    attempts: int
    events: List[FailureEvent] = field(default_factory=list)
    hosts: int = 0  # live hosts at completion
    mesh: Any = None  # the mesh the successful attempt ran on

    @property
    def recoveries(self) -> int:
        return len(self.events)


# ---------------------------------------------------------------------------
# host board: heartbeat ledger + quarantine state
# ---------------------------------------------------------------------------

class HostBoard:
    """Per-host state shared between the fit thread (boundary pulses)
    and the monitor (heartbeat refresh + age checks). Hosts are the
    contiguous device groups of the ORIGINAL mesh (`mesh.host_groups`);
    quarantine removes a group from every future mesh re-form."""

    def __init__(self, mesh, hosts: int):
        self.groups = mesh_lib.host_groups(mesh, hosts)
        self.num_hosts = len(self.groups)
        self._lock = threading.Lock()
        now = time.monotonic()
        self.last_beat: Dict[int, float] = {h: now for h in range(self.num_hosts)}
        self._dead: set = set()  # heartbeat sender stopped (this attempt)
        self._hung: Optional[int] = None  # last boundary's non-participant
        self._hung_phase: Optional[str] = None
        self._quarantined: set = set()  # removed from mesh re-forms

    # -- membership ---------------------------------------------------------
    def live(self) -> List[int]:
        with self._lock:
            return [h for h in range(self.num_hosts) if h not in self._quarantined]

    def live_count(self) -> int:
        return len(self.live())

    def form_mesh(self):
        """Re-form the data mesh over the survivors' devices."""
        with self._lock:
            groups = [
                g
                for h, g in enumerate(self.groups)
                if h not in self._quarantined and g
            ]
        return mesh_lib.form_mesh_over(groups)

    # -- failure simulation hooks (called from the FIT thread) ---------------
    def mark_dead(self, host: int, phase: str) -> None:
        """The victim's heartbeat sender stops — from here on its beat
        only ages; the monitor detects through that signal alone."""
        with self._lock:
            self._dead.add(host)
            self._hung, self._hung_phase = host, phase

    def mark_hung(self, host: int, phase: str) -> None:
        """The victim never enters this boundary (collective-entry
        attribution for the hang report); its heartbeat KEEPS going."""
        with self._lock:
            self._hung, self._hung_phase = host, phase

    def any_dead(self) -> bool:
        with self._lock:
            return bool(self._dead)

    def hung_host(self):
        with self._lock:
            return self._hung, self._hung_phase

    # -- heartbeats (monitor side) ------------------------------------------
    def beat_live(self, now: float) -> None:
        """Animate the side-channel heartbeat senders: every live,
        not-dead host beats. A die-marked host's sender stopped — its
        beat ages until the timeout detector fires."""
        with self._lock:
            for h in range(self.num_hosts):
                if h not in self._quarantined and h not in self._dead:
                    self.last_beat[h] = now

    def overdue(self, now: float, timeout_s: float) -> List[tuple]:
        """(host, age_s) pairs past the heartbeat timeout."""
        with self._lock:
            out = []
            for h in range(self.num_hosts):
                if h in self._quarantined:
                    continue
                age = now - self.last_beat[h]
                if age > timeout_s:
                    out.append((h, age))
            return out

    # -- recovery ------------------------------------------------------------
    def quarantine(self, host: int) -> None:
        with self._lock:
            self._quarantined.add(host)
        metrics.inc_counter("supervisor.quarantine")

    def readmit_reset(self) -> None:
        """Start the next attempt with a clean slate for non-quarantined
        hosts: beats refreshed, death/hang marks cleared (a re-admitted
        hung host is considered recovered once the attempt restarts)."""
        now = time.monotonic()
        with self._lock:
            self._dead.clear()
            self._hung, self._hung_phase = None, None
            for h in range(self.num_hosts):
                if h not in self._quarantined:
                    self.last_beat[h] = now


# ---------------------------------------------------------------------------
# the per-attempt context + the module-level hook surface
# ---------------------------------------------------------------------------

class SupervisorContext:
    """One supervised attempt's shared state. The fit thread pulses
    boundaries and progress through the module-level hooks; the monitor
    reads timestamps and flips the abort event. Hooks are bound to the
    worker thread's ident, so a late pulse from a previous (aborted)
    attempt can never leak into the current one."""

    def __init__(self, board: HostBoard, *, victim_host: Optional[int],
                 stall_safety_s: float):
        from .. import config

        self.board = board
        self.victim_host = victim_host
        self.stall_safety_s = float(stall_safety_s)
        self._abort = threading.Event()
        self.worker_ident: Optional[int] = None
        # chunk-wall EMA — the hang deadline's basis (escalate=0: THIS
        # watchdog reports through typed failures, never by raising)
        self.watchdog = flow.StragglerWatchdog(
            "supervisor.chunk", factor=config.hang_factor, warmup=1, escalate=0
        )
        self.progress_at: Optional[float] = None
        self.first_progress_at: Optional[float] = None
        self.fault_visible_at: Optional[float] = None
        self.fault_phase: Optional[str] = None

    # -- monitor side --------------------------------------------------------
    def abort(self) -> None:
        self._abort.set()

    @property
    def aborted(self) -> bool:
        return self._abort.is_set()

    def hang_deadline_s(self) -> Optional[float]:
        """None until a first chunk-wall sample exists (a cold compile
        must not count against the deadline)."""
        from .. import config

        if self.watchdog.samples < 1 or self.progress_at is None:
            return None
        return max(
            config.hang_min_deadline_s,
            config.hang_factor * self.watchdog.trailing_mean_s,
        )

    # -- fit-thread side -----------------------------------------------------
    def _victim(self) -> int:
        live = self.board.live()
        if self.victim_host is not None and self.victim_host in live:
            return self.victim_host
        return live[-1]

    def note_progress(self, wall_s: Optional[float] = None) -> None:
        now = time.monotonic()
        self.progress_at = now
        if self.first_progress_at is None:
            self.first_progress_at = now
        if wall_s is not None:
            self.watchdog.record(wall_s)

    def _note_gap(self) -> None:
        """Fold the inter-boundary gap into the chunk-wall EMA. This is
        what arms the hang detector (samples >= 1) and what makes it
        compile-safe without special-casing: the FIRST boundary records
        nothing (the detector stays disarmed across the attempt's cold
        compile), the second folds a gap that INCLUDES any compile — a
        large first sample the EMA decays from — and steady-state gaps
        track the chunk wall even on fits that bypass `timed_dispatch`
        (the out-of-core epoch loops' commit-only boundaries)."""
        now = time.monotonic()
        if self.progress_at is not None:
            self.watchdog.record(now - self.progress_at)

    def boundary(self, phase: str) -> None:
        """One supervised boundary: abort check, fault-site ticks, then a
        progress note. A fired `host.die` stops the victim's heartbeats;
        a fired `host.hang` — and every boundary while a peer is dead
        (survivors can't clear the collective without it) — stalls the
        fit thread until the monitor aborts the attempt."""
        if self._abort.is_set():
            raise SupervisorAbort(phase)
        board = self.board
        if board.any_dead():
            self._stall(phase)
        try:
            faults.tick("host.die")
            faults.tick(f"host.die.{phase}")
        except faults.InjectedFault:
            victim = self._victim()
            board.mark_dead(victim, phase)
            self._note_fault(phase)
            self._stall(phase)
        try:
            faults.tick("host.hang")
            faults.tick(f"host.hang.{phase}")
        except faults.InjectedFault:
            victim = self._victim()
            board.mark_hung(victim, phase)
            self._note_fault(phase)
            self._stall(phase)
        self._note_gap()
        self.note_progress()

    def _note_fault(self, phase: str) -> None:
        self.fault_visible_at = time.monotonic()
        self.fault_phase = phase

    def _stall(self, phase: str) -> None:
        """Block like a wedged collective until the supervisor aborts,
        then unwind. The safety timeout exists so a monitor bug can
        never deadlock a test run — hitting it is itself an error."""
        metrics.inc_counter("supervisor.stall")
        if timeline.enabled():
            timeline.record_instant(
                timeline.LANE_SUPERVISOR, "supervisor.stall", phase=phase
            )
        deadline = time.monotonic() + self.stall_safety_s
        while not self._abort.wait(0.02):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"supervised fit stalled at the {phase} boundary for "
                    f"{self.stall_safety_s}s without a supervisor abort — "
                    "the monitor is not running or its detectors are off"
                )
        raise SupervisorAbort(phase)


_active: Optional[SupervisorContext] = None


def active() -> Optional[SupervisorContext]:
    """The running attempt's context when called FROM its fit thread
    (ident-bound), else None — the hooks' fast path."""
    ctx = _active
    if ctx is None or ctx.worker_ident != threading.get_ident():
        return None
    return ctx


def pulse_boundary(phase: str) -> None:
    """Supervised-boundary hook for the dispatch/drain/commit sites
    (`dispatch.timed_dispatch`, `DrainQueue`, the snapshot commit path).
    No-op outside a supervised fit."""
    ctx = active()
    if ctx is not None:
        ctx.boundary(phase)


def note_progress(wall_s: Optional[float] = None) -> None:
    """Progress hook: stamps the hang watchdog's last-progress time and
    (when given) folds one chunk-wall sample into its EMA. No-op outside
    a supervised fit."""
    ctx = active()
    if ctx is not None:
        ctx.note_progress(wall_s)


# ---------------------------------------------------------------------------
# supervise: run a fit under the host-health protocol
# ---------------------------------------------------------------------------

def _sweep_in_flight_cut(checkpoint_dir: Optional[str], job_key: Optional[str]) -> int:
    if checkpoint_dir is None:
        return 0
    from ..ckpt import coordinator

    swept = coordinator.sweep_uncommitted(checkpoint_dir, job_key)
    if swept:
        metrics.inc_counter("supervisor.cutSwept", swept)
    return swept


def supervise(
    fit: Callable[[Any], Any],
    *,
    hosts: Optional[int] = None,
    mesh=None,
    checkpoint_dir: Optional[str] = None,
    job_key: Optional[str] = None,
    victim_host: Optional[int] = None,
    on_hang: str = "readmit",
    on_failure: str = "shrink",
    recovery_budget: Optional[int] = None,
    heartbeat_timeout_s: Optional[float] = None,
    poll_interval_s: Optional[float] = None,
    stall_safety_s: float = 60.0,
) -> SupervisedResult:
    """Run `fit(mesh) -> value` under the host-health protocol.

    `fit` must be a resumable checkpointed fit: it restores its own
    newest committed cut on entry (the SGD/KMeans/`iterate_bounded`
    contract) and accepts the mesh to run on — re-running it after a
    quarantine IS the recovery. `hosts` defaults to
    `config.snapshot_hosts` (falling back to 1); when sharded snapshots
    are on, each attempt scopes `config.snapshot_hosts` to the live host
    count so shard ownership tracks the surviving mesh.

    Policies: `on_hang` — "readmit" (default: a hung host is stuck, not
    gone; the attempt aborts and resumes on the SAME host count, which
    keeps the resume bit-identical to an unkilled fit) or "shrink";
    `on_failure` — "shrink" (default: a dead host is quarantined and the
    mesh re-forms without it; cross-count resume is allclose per the
    reduction-order caveat) or "readmit" (a host expected back).

    Raises `RecoveryBudgetExhausted` past `recovery_budget` recoveries
    (default `config.recovery_budget`); any NON-supervised fit error
    (data errors, injected kills at other sites) propagates untouched —
    the supervisor recovers from host failures, it does not launder
    bugs into retries.
    """
    global _active
    from .. import config

    mesh = mesh if mesh is not None else mesh_lib.default_mesh()
    n_hosts = hosts if hosts is not None else (config.snapshot_hosts or 1)
    budget = (
        config.recovery_budget if recovery_budget is None else int(recovery_budget)
    )
    hb_timeout = (
        config.host_heartbeat_timeout_s
        if heartbeat_timeout_s is None
        else float(heartbeat_timeout_s)
    )
    poll = (
        config.supervisor_poll_interval_s
        if poll_interval_s is None
        else float(poll_interval_s)
    )
    sharded = config.snapshot_hosts is not None
    if on_hang not in ("readmit", "shrink"):
        raise ValueError(f"unknown on_hang policy {on_hang!r}")
    if on_failure not in ("readmit", "shrink"):
        raise ValueError(f"unknown on_failure policy {on_failure!r}")

    board = HostBoard(mesh, n_hosts)
    events: List[FailureEvent] = []
    attempt = 0
    recovered_at: Optional[float] = None  # detection end of the last failure

    while True:
        attempt += 1
        board.readmit_reset()
        mesh_now = board.form_mesh()
        metrics.set_gauge("supervisor.hosts", board.live_count())
        ctx = SupervisorContext(
            board, victim_host=victim_host, stall_safety_s=stall_safety_s
        )
        result_ch = flow.BoundedChannel(1, name="supervisor.result")

        def run(ctx=ctx, mesh_now=mesh_now, result_ch=result_ch):
            ctx.worker_ident = threading.get_ident()
            try:
                if sharded:
                    with config.snapshot_hosts_mode(board.live_count()):
                        value = fit(mesh_now)
                else:
                    value = fit(mesh_now)
                result_ch.put(("ok", value))
            except SupervisorAbort as e:
                result_ch.put(("aborted", e))
            except BaseException as e:  # noqa: BLE001 — channel IS the error path
                result_ch.close(error=e)

        _active = ctx
        worker = flow.spawn(run, name="supervised-fit")
        failure: Optional[BaseException] = None
        outcome = None
        try:
            while outcome is None and failure is None:
                try:
                    outcome = result_ch.get(timeout=poll)
                except TimeoutError:
                    pass
                now = time.monotonic()
                board.beat_live(now)
                overdue = board.overdue(now, hb_timeout)
                if overdue:
                    host, age = overdue[0]
                    _, phase = board.hung_host()
                    failure = HostFailure(host, age, phase)
                    break
                deadline = ctx.hang_deadline_s()
                if deadline is not None and now - ctx.progress_at > deadline:
                    hung, phase = board.hung_host()
                    failure = CollectiveHang(
                        now - ctx.progress_at, deadline, hung, phase
                    )
                    break
        finally:
            if failure is not None or outcome is None:
                ctx.abort()
            if outcome is None:
                # wait for the aborted worker to unwind and report; a
                # worker error is already propagating out of the get in
                # the monitor loop above, so never let a re-raise here
                # skip the join and the deactivation below
                try:
                    outcome = result_ch.get(timeout=stall_safety_s)
                except BaseException:  # noqa: BLE001 — see comment above
                    outcome = None
            worker.join(timeout=stall_safety_s)
            _active = None

        if failure is None and outcome is not None and outcome[0] == "ok":
            if events and events[-1].recovery_ms is None and recovered_at is not None:
                first = ctx.first_progress_at
                events[-1].recovery_ms = (
                    ((first if first is not None else time.monotonic())
                     - recovered_at) * 1000.0
                )
                metrics.set_gauge("supervisor.recoveryMs", events[-1].recovery_ms)
            metrics.set_gauge("supervisor.hosts", board.live_count())
            return SupervisedResult(
                value=outcome[1],
                attempts=attempt,
                events=events,
                hosts=board.live_count(),
                mesh=mesh_now,
            )
        if failure is None:
            # the worker itself surfaced a typed host failure or died on a
            # non-supervised error: propagate the real thing
            if outcome is not None and isinstance(outcome[1], SupervisorAbort):
                raise RuntimeError(
                    "supervised fit aborted without a recorded failure — "
                    "monitor/worker handshake bug"
                )
            raise RuntimeError("supervised fit ended without outcome or failure")

        # ---- detection bookkeeping ----------------------------------------
        now = time.monotonic()
        visible = ctx.fault_visible_at if ctx.fault_visible_at is not None else (
            ctx.progress_at if ctx.progress_at is not None else now
        )
        detection_ms = max(0.0, (now - visible) * 1000.0)
        kind = "hostFailure" if isinstance(failure, HostFailure) else "collectiveHang"
        metrics.inc_counter(f"supervisor.{kind}")
        metrics.set_gauge("supervisor.detectionMs", detection_ms)
        if timeline.enabled():
            timeline.record_instant(
                timeline.LANE_SUPERVISOR,
                "supervisor.detect",
                kind=kind,
                host=-1 if failure.host is None else int(failure.host),
                phase=failure.phase or "",
                detectionMs=detection_ms,
            )

        # fill the PREVIOUS failure's recovery wall if this attempt got far
        # enough to make progress before failing again
        if events and events[-1].recovery_ms is None and recovered_at is not None:
            first = ctx.first_progress_at
            if first is not None:
                events[-1].recovery_ms = (first - recovered_at) * 1000.0

        # ---- recovery: quarantine, sweep, re-form, resume ------------------
        policy = on_failure if kind == "hostFailure" else on_hang
        quarantined = policy == "shrink" and failure.host is not None
        if quarantined:
            board.quarantine(int(failure.host))
        swept = _sweep_in_flight_cut(checkpoint_dir, job_key)
        events.append(
            FailureEvent(
                kind=kind,
                host=failure.host,
                phase=failure.phase,
                detection_ms=detection_ms,
                quarantined=quarantined,
                hosts_after=board.live_count(),
            )
        )
        if len(events) > budget:
            raise RecoveryBudgetExhausted(events) from failure
        if not any(board.groups[h] for h in board.live()):
            raise RecoveryBudgetExhausted(events) from failure
        metrics.inc_counter("supervisor.recovery")
        recovered_at = time.monotonic()
        if timeline.enabled():
            timeline.record_instant(
                timeline.LANE_SUPERVISOR,
                "supervisor.recover",
                attempt=attempt,
                hosts=board.live_count(),
                swept=swept,
            )
