"""Bounded and unbounded iteration runtime.

TPU-native replacement for flink-ml-iteration (17,323 LoC): the reference
needs HeadOperator/TailOperator, epoch watermarks, a feedback channel and a
JobManager-side SharedProgressAligner because its operators run
asynchronously on a streaming engine (Iterations.java:144-170,
HeadOperator.java:101-117, SharedProgressAligner.java:127). Under SPMD the
whole problem disappears: a jitted `lax.while_loop` whose carry is the
model state IS the feedback edge, and a `psum` inside the body IS the
globally-aligned epoch. What remains worth keeping from the reference is
the *semantics*: maxIter/tol termination criteria
(common/iteration/TerminateOnMaxIter.java:56, TerminateOnMaxIterOrTol.java:72),
per-epoch listener callbacks (IterationListener.java:75), replayed datasets
(ReplayOperator.java — here: the dataset is resident on device and every
epoch re-reads it), and checkpoint/resume (here: epoch boundary = consistent
state; a checkpoint is (carry, epoch, criteria) written at epoch boundaries,
vs the reference's in-flight feedback-record logging, Checkpoints.java:92-143).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs import tracing

BodyFn = Callable[[Any, jax.Array], Tuple[Any, jax.Array]]


class IterationListener:
    """Per-epoch callbacks (iteration/IterationListener.java:75). Using a
    listener forces the host-driven loop (one jitted epoch per host step)
    instead of the fully on-device while_loop."""

    def on_epoch_watermark_incremented(self, epoch: int, carry) -> None:
        ...

    def on_iteration_terminated(self, carry) -> None:
        ...


@dataclass
class IterationResult:
    carry: Any
    num_epochs: int
    final_criteria: float


# ---------------------------------------------------------------------------
# checkpointing: epoch-boundary snapshots of the carry pytree
# ---------------------------------------------------------------------------

def checkpoint_job_key(stage, exclude=("maxIter", "tol")) -> str:
    """Stable job-identity key for checkpoint namespacing: estimator class
    name + a hash of its params. Two jobs with identical carry STRUCTURE
    but different hyper-parameters (e.g. two OnlineKMeans runs with the
    same k and d) then write different checkpoint files under a shared
    `config.iteration_checkpoint_dir` instead of silently cross-restoring.

    Termination-schedule params (`maxIter`, `tol`) are excluded by
    default: resuming an interrupted run with a larger maxIter is the
    canonical resume pattern and must map to the SAME job."""
    import hashlib

    params = {}
    for p, v in stage.get_param_map().items():
        if p.name in exclude:
            continue
        try:
            params[p.name] = p.json_encode(v)
        except Exception:
            params[p.name] = repr(v)
    blob = json.dumps(params, sort_keys=True, default=repr)
    digest = hashlib.sha1(blob.encode()).hexdigest()[:10]
    return f"{type(stage).__name__}-{digest}"


def _checkpoint_file(path: str, job_key: Optional[str]) -> str:
    if job_key is None:
        return os.path.join(path, "ckpt.npz")
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", job_key)
    return os.path.join(path, f"ckpt-{safe}.npz")


def save_iteration_checkpoint(
    path: str, carry, epoch: int, criteria: float, job_key: Optional[str] = None
) -> None:
    """LEGACY carry-only writer, kept for direct users and as the
    migration source: the iteration loops themselves now snapshot through
    the versioned JobSnapshot format (flink_ml_tpu/ckpt/snapshot.py),
    whose loader also reads files this function wrote (one-way)."""
    from ..utils.packing import packed_device_get

    leaves = jax.tree_util.tree_leaves(carry)
    # one packed D2H transfer for the whole carry (a per-leaf np.asarray
    # pull costs one tunnel round trip PER LEAF); counted as a checkpoint
    # host sync so BENCH deltas separate snapshot cost from drain cost
    leaves = packed_device_get(*leaves, sync_kind="checkpoint")
    os.makedirs(path, exist_ok=True)
    target = _checkpoint_file(path, job_key)
    tmp = target[: -len(".npz")] + ".tmp.npz"  # keep .npz so savez won't rename
    np.savez(
        tmp,
        epoch=np.int64(epoch),
        criteria=np.float64(criteria),
        **{f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)},
    )
    os.replace(tmp, target)


def load_iteration_checkpoint(path: str, carry_like, job_key: Optional[str] = None):
    """Restore (carry, epoch, criteria) from `path`, or None if absent OR
    structurally incompatible. With a `job_key` (see `checkpoint_job_key`)
    the lookup is namespaced per job, so structurally-identical jobs
    sharing a directory stay isolated; un-keyed restores WARN, because the
    structural guard alone cannot tell two same-shaped jobs apart (leaves
    restore positionally against `carry_like`'s treedef — a foreign but
    compatible checkpoint would silently train from foreign state).

    Reads the versioned JobSnapshot format first (the format the loops
    write since the ckpt/ subsystem landed) and falls back to the legacy
    carry-only npz this module used to write — both through
    `ckpt.snapshot.load_job_snapshot`, so the guards live in one place."""
    from ..ckpt import snapshot as _snapshot

    snap = _snapshot.load_job_snapshot(path, job_key, templates={"model": carry_like})
    if snap is None:
        return None
    return snap.sections["model"], snap.epoch, snap.criteria


# ---------------------------------------------------------------------------
# bounded iteration
# ---------------------------------------------------------------------------

def iterate_bounded(
    body: BodyFn,
    init_carry,
    max_iter: int,
    tol: Optional[float] = None,
    listener: Optional[IterationListener] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_interval: int = 1,
    chunk_size: Optional[int] = None,
    job_key: Optional[str] = None,
) -> IterationResult:
    """Run `body(carry, epoch) -> (carry, criteria)` until termination.

    Termination mirrors TerminateOnMaxIterOrTol.java:72: stop when
    `epoch >= max_iter` or (if `tol` is set) `criteria <= tol`. With no
    listener and no checkpointing the whole loop compiles to one XLA
    while-loop (the feedback edge never leaves the device). With a
    listener, each epoch is one jitted device step (the analogue of
    ALL_ROUND operators observing epoch watermarks); with checkpointing
    only, epochs run in K-sized chunks (`chunk_size`, default from
    config.iteration_chunk_for) with one packed convergence readback per
    chunk — the stop epoch and final carry are identical to the per-epoch
    loop for any K because the tol check still runs every epoch inside
    the chunk program (see docs/performance.md).
    """
    if listener is None and checkpoint_dir is None:
        return _iterate_on_device(body, init_carry, max_iter, tol)
    return _iterate_host_driven(
        body,
        init_carry,
        max_iter,
        tol,
        listener,
        checkpoint_dir,
        checkpoint_interval,
        chunk_size,
        job_key,
    )


def _iterate_on_device(body: BodyFn, init_carry, max_iter: int, tol: Optional[float]):
    from ..utils import metrics, packing

    tol_value = -jnp.inf if tol is None else jnp.asarray(float(tol), jnp.float32)

    def cond(state):
        _, epoch, criteria = state
        return jnp.logical_and(epoch < max_iter, criteria > tol_value)

    def step(state):
        carry, epoch, _ = state
        new_carry, criteria = body(carry, epoch)
        return new_carry, epoch + 1, jnp.asarray(criteria, jnp.float32)

    init_state = (init_carry, jnp.asarray(0, jnp.int32), jnp.asarray(jnp.inf, jnp.float32))
    # the whole loop is one XLA program, so per-epoch spans are impossible
    # here by design — a single `iteration.run` span carries the per-run
    # summary (epoch count, final criteria) instead
    with tracing.span("iteration.run", mode="device") as sp:
        with metrics.timed("iteration.device_loop"):
            # body is a per-call closure: a cached wrapper can never be
            # reused at this layer (chunked loops ride dispatch.chunk_runner
            # instead, which caches per body object)
            # tpulint: disable=retrace-hazard -- per-fit body closure; one dispatch per fit, reuse impossible here
            carry, epochs, criteria = jax.jit(
                lambda s: lax.while_loop(cond, step, s)
            )(init_state)
            # the loop's one convergence drain, through the accounted
            # funnel (doubles as the barrier that keeps the timing honest)
            epochs_h, criteria_h = packing.packed_device_get(
                epochs, criteria, sync_kind="drain"
            )
        num_epochs, final = int(epochs_h), float(criteria_h)
        sp.set_attr("epochs", num_epochs)
        sp.set_attr("finalCriteria", final)
    metrics.set_gauge("iteration.epochs", num_epochs)
    return IterationResult(carry, num_epochs, final)


def _iterate_host_driven(
    body,
    init_carry,
    max_iter,
    tol,
    listener,
    checkpoint_dir,
    checkpoint_interval,
    chunk_size=None,
    job_key=None,
):
    """Pipelined host-driven loop.

    With a listener, each epoch is one dispatched program (the listener
    contract exposes every (epoch, carry) pair); with checkpointing only,
    K epochs fuse into one chunk program whose ends clamp to checkpoint
    boundaries. Either way, dispatched steps queue up to
    `config.iteration_dispatch_depth` deep before their packed
    (epoch, criteria) scalars are drained, so host Python overlaps device
    execution instead of serializing on every convergence readback.

    Exactness under speculation: every dispatched step is criteria-guarded
    on device (the chunk's while condition re-checks `criteria > tol`
    before each epoch), so steps dispatched past the tol-fire epoch are
    identity programs — the final carry, stop epoch, and stop criteria
    are bit-identical to the fully synchronous per-epoch loop.
    """
    from .. import config
    from ..ckpt import faults
    from ..ckpt import snapshot as _snapshot
    from ..utils import metrics
    from . import dispatch

    carry, epoch, criteria = init_carry, 0, float("inf")
    if checkpoint_dir is not None:
        restored = load_iteration_checkpoint(checkpoint_dir, init_carry, job_key)
        if restored is not None:
            carry, epoch, criteria = restored

    per_epoch = listener is not None
    K = 1 if per_epoch else config.iteration_chunk_for(max_iter, chunk_size)
    # Whole-fit resident program (config.whole_fit): with no listener and
    # no snapshot boundary strictly inside the remaining loop, the chunk
    # program covers the ENTIRE fit (K = remaining epochs) — one dispatch,
    # one packed readback, and the existing fit-end-boundary snapshot
    # logic below still fires on the retained carry. A listener or a
    # mid-fit boundary falls back to the chunked path (reason-counted).
    take_whole, _ = dispatch.whole_fit_plan(
        start_epoch=epoch,
        max_iter=max_iter,
        checkpoint_interval=(
            checkpoint_interval if checkpoint_dir is not None else None
        ),
        listener=per_epoch,
    )
    if take_whole:
        dispatch.account_whole_fit("iterate")
        K = max(1, max_iter - epoch)
    runner = dispatch.chunk_runner(body)
    donate_ok = dispatch.supports_donation()
    tol_value = jnp.asarray(-jnp.inf if tol is None else float(tol), jnp.float32)

    epoch_dev = jnp.asarray(epoch, jnp.int32)
    crit_dev = jnp.asarray(criteria, jnp.float32)
    queue = dispatch.DrainQueue(config.iteration_dispatch_depth)
    final_epoch, final_crit = epoch, criteria
    stopped = tol is not None and criteria <= tol

    def handle(drained):
        nonlocal final_epoch, final_crit, stopped
        for entry, e_act, crit in drained:
            advanced = e_act > final_epoch
            final_epoch, final_crit = e_act, crit
            metrics.set_gauge("iteration.epochs", final_epoch)
            if not advanced:
                continue  # speculative identity step past the stop epoch
            if per_epoch:
                listener.on_epoch_watermark_incremented(e_act, entry.carry)
            if (
                checkpoint_dir is not None
                and e_act == entry.end
                and e_act % checkpoint_interval == 0
            ):
                _snapshot.save_job_snapshot(
                    checkpoint_dir,
                    job_key,
                    {"model": entry.carry},
                    epoch=e_act,
                    criteria=crit,
                )
            if tol is not None and crit <= tol:
                stopped = True
            faults.tick("chunk")

    mode = "host" if per_epoch else "chunked"
    with tracing.span(
        "iteration.run", mode=mode, chunk=K, depth=queue.depth
    ) as run_sp:
        planned = epoch
        donate_next = False  # never consume the caller's init carry
        while planned < max_iter and not stopped:
            end = min(planned + K, max_iter)
            boundary = dispatch.next_boundary(
                planned, checkpoint_interval if checkpoint_dir is not None else None
            )
            if boundary is not None:
                end = min(end, boundary)
            # retain the post-chunk carry when the drain handler will need
            # it on host (listener callback / checkpoint snapshot) — a
            # retained carry must not be donated into the next dispatch
            retain = per_epoch or (
                checkpoint_dir is not None and end % checkpoint_interval == 0
            )
            step = runner.donating if (donate_next and donate_ok) else runner.borrowing
            with tracing.span(
                "iteration.epoch" if per_epoch else "iteration.chunk",
                epoch=planned,
                **({} if per_epoch else {"end": end}),
            ):
                with metrics.timed("iteration.epoch" if per_epoch else "iteration.chunk"):
                    carry, epoch_dev, crit_dev, packed = dispatch.timed_dispatch(
                        step,
                        carry, epoch_dev, crit_dev,
                        jnp.asarray(end, jnp.int32), tol_value,
                        start=planned, end=end,
                    )
            handle(
                queue.push(
                    dispatch.InFlight(planned, end, carry if retain else None, packed)
                )
            )
            planned = end
            donate_next = not retain
        handle(queue.drain_all())
        run_sp.set_attr("epochs", final_epoch)
        run_sp.set_attr("finalCriteria", final_crit)

    if listener is not None:
        listener.on_iteration_terminated(carry)
    return IterationResult(carry, final_epoch, final_crit)


def scan_epochs(body: BodyFn, init_carry, num_epochs: int):
    """Fixed-epoch variant returning the per-epoch criteria history, compiled
    as one `lax.scan` (useful for loss curves / benchmarks)."""

    def step(carry, epoch):
        new_carry, criteria = body(carry, epoch)
        return new_carry, criteria

    # tpulint: disable=retrace-hazard -- per-call body closure (bench/loss-curve helper); one dispatch per call
    carry, history = jax.jit(
        lambda c: lax.scan(step, c, jnp.arange(num_epochs, dtype=jnp.int32))
    )(init_carry)
    return carry, history


# ---------------------------------------------------------------------------
# unbounded (online) iteration
# ---------------------------------------------------------------------------

def iterate_unbounded(
    batches: Iterable,
    step: Callable[[Any, Any], Any],
    init_state,
    listener: Optional[IterationListener] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_interval: Optional[int] = None,
    job_key: Optional[str] = None,
) -> Iterable[Tuple[int, Any]]:
    """Host-driven online loop (Iterations.iterateUnboundedStreams:118-131).

    For each incoming global mini-batch, advance the model state and publish
    a new model version — the analogue of the online estimators' feedback
    loop with `countWindowAll` global batches and the `modelDataVersion`
    gauge (OnlineKMeans.java:44-60, OnlineKMeansModel.java:166). Yields
    (model_version, state) after every batch.

    Checkpoint/resume: with a checkpoint dir (explicit args or the
    process-wide `config.iteration_checkpoint_dir`), the (state, version)
    pair is snapshotted at global-batch boundaries — the version IS the
    stream position in global batches, so on restart against a replayed
    source the already-folded prefix is skipped and training continues
    exactly where it stopped. This is the SPMD analogue of the reference's
    unbounded iteration riding Flink's exactly-once checkpointing
    (iteration/checkpoint/Checkpoints.java:43-143: snapshot the operator
    state + in-flight feedback records; here a batch boundary is the only
    consistent cut, so there are no in-flight records to log).
    """
    from ..ckpt import faults
    from ..ckpt import snapshot as _snapshot

    if checkpoint_dir is None:
        from .. import config

        checkpoint_dir = config.iteration_checkpoint_dir
        # an explicit interval wins even when the DIR comes from config —
        # callers tuning snapshot cadence must not depend on where the
        # directory was resolved from
        interval = checkpoint_interval or config.iteration_checkpoint_interval
    else:
        interval = checkpoint_interval or 1

    state = init_state
    version = 0
    if checkpoint_dir is not None:
        restored = load_iteration_checkpoint(checkpoint_dir, init_state, job_key)
        if restored is not None:
            state, version, _ = restored
            # republish the restored model immediately so a serving model
            # reaches the checkpointed version before the next live batch
            yield version, state
    skip = version
    for batch in batches:
        if skip > 0:  # replayed prefix already folded into the checkpoint
            skip -= 1
            continue
        with tracing.span("iteration.epoch", epoch=version, mode="unbounded"):
            state = step(state, batch)
        version += 1
        if listener is not None:
            listener.on_epoch_watermark_incremented(version, state)
        if checkpoint_dir is not None and version % interval == 0:
            # the version IS the stream offset in global batches — stored
            # in meta so a resume against a non-replayed source is caught
            _snapshot.save_job_snapshot(
                checkpoint_dir,
                job_key,
                {"model": state},
                epoch=version,
                meta={"streamOffset": version},
            )
        faults.tick("batch")
        yield version, state
    if checkpoint_dir is not None:
        # the stream completed: clear the checkpoint so a NEW job reusing
        # this dir does not resume from (and skip past) a finished run —
        # sharded cuts (manifests + shards) included
        from ..ckpt import coordinator as _coordinator

        for file in (
            _snapshot.snapshot_file(checkpoint_dir, job_key),
            _checkpoint_file(checkpoint_dir, job_key),
        ):
            if os.path.exists(file):
                os.remove(file)
        _coordinator.purge(checkpoint_dir, job_key)
    if listener is not None:
        listener.on_iteration_terminated(state)
