"""Distributed communication primitives.

The reference builds three comm primitives on Flink's netty shuffle
(SURVEY.md §5): a chunked emulated all-reduce
(common/datastream/AllReduceImpl.java:56-103, 32KB chunks over two
partitionCustom shuffles), broadcast variables (BroadcastUtils.java:64),
and the statefun in-JVM feedback channel (operator/TailOperator.java:76-79).
On TPU these are hardware collectives over ICI; `psum` IS the all-reduce
and replication IS the broadcast — but the reference's chunk decomposition
is worth keeping: a large gradient reduced as one monolithic collective
cannot overlap anything, while size-targeted buckets can pipeline against
each other and against compute. This module therefore carries two tiers:

- thin accounted wrappers over the hardware collectives (`all_reduce_sum`
  … `ppermute_ring`) — every collective a model dispatches rides one of
  these, so `collective.*` counters answer "what traffic does this program
  move";
- the comm layer proper: `all_reduce_sum_chunked` (bucketed
  reduce_scatter+all_gather with a ring-pipelined ppermute variant) and
  `sparse_all_reduce_sum` (SparCML-style index-value reduction, wire bytes
  ∝ nnz instead of dim — arXiv:1802.08021). Both are bit-identical to a
  single `lax.psum` of the same operand (pinned across chunk sizes and
  shard counts by tests/test_collective_chunks.py); the overlap-scheduled
  training loops in parallel/overlap.py are built on them.

These wrappers are used inside `shard_map`-ped functions; outside
`shard_map`, prefer sharding annotations and let XLA insert collectives.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analysis import sanitizer as _sanitizer
from ..obs import hist, tracing
from ..utils import metrics

# the axis-name constants are DECLARED in mesh.py and re-exported here so
# model/ops code that already imports collectives needs no second import —
# the mesh-axis lint rule resolves either path to the same constant
from .mesh import DATA_AXIS, MODEL_AXIS  # noqa: F401  (MODEL_AXIS re-export)


def _iter_array_leaves(x):
    """Every array-like leaf of a possibly-nested structure. Unlike a bare
    `tree_leaves` + hasattr filter, this also descends containers that are
    not registered pytrees and never drops a level: a sparse (indices,
    values) tuple nested inside a gradient pytree contributes BOTH leaves
    to the byte count (the round-5 accounting undercounted these)."""
    if isinstance(x, (tuple, list)):
        for item in x:
            yield from _iter_array_leaves(item)
    elif isinstance(x, dict):
        for item in x.values():
            yield from _iter_array_leaves(item)
    elif hasattr(x, "shape") and hasattr(x, "dtype"):
        yield x
    else:
        try:
            for leaf in jax.tree_util.tree_leaves(x):
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                    yield leaf
        except Exception:
            pass


def payload_bytes(x) -> int:
    """Per-participant payload bytes of a pytree: the sum over every array
    leaf, including leaves of nested non-pytree containers."""
    return sum(
        int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        for leaf in _iter_array_leaves(x)
    )


def _account(op: str, x, axis_name: str, chunks: int = None, dense_equiv_bytes: int = None) -> None:
    """Record one collective call: op, per-participant payload bytes and
    chunk (bucket/leaf) count. These wrappers run INSIDE jitted/shard_map
    code, so this fires at TRACE time — once per compiled program, not per
    execution — which is exactly when the op's shape is known; the
    counters answer "what collective traffic does this program dispatch",
    the device profile answers how long it took."""
    leaves = list(_iter_array_leaves(x))
    nbytes = sum(int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize for leaf in leaves)
    # sanitizer collective-sequence ledger (FLINK_ML_TPU_SANITIZE=1): the
    # per-shard (op, axis, shape, dtype) sequence must match across shard
    # scopes at exit — the dynamic dual of the collective-divergence rule
    if leaves:
        _sanitizer.record_collective(
            op, axis_name, leaves[0].shape, np.dtype(leaves[0].dtype).name
        )
    else:
        _sanitizer.record_collective(op, axis_name, (), "none")
    # payload-SIZE distribution (SparCML-style evaluation: per-collective
    # size histograms, not just byte sums — a p99 payload far above p50
    # says the bucketing layer is emitting stragglers)
    hist.record("collective.payloadBytes", nbytes)
    # per-AXIS attribution: on a 2D (data, model) mesh the two axes carry
    # different traffic classes (nnz-proportional gradient pairs over
    # `data`, active-feature slices over `model`), so the wire-byte
    # evidence must not collapse into one counter — the sparse2dMesh
    # BENCH entry reads these to report per-axis wire bytes, and the
    # per-axis sparse ratio keeps a model-axis reduce from diluting the
    # data-axis traffic-proportionality claim
    metrics.inc_counter(f"collective.axis.{axis_name}.calls")
    metrics.inc_counter(f"collective.axis.{axis_name}.bytes", int(nbytes))
    if dense_equiv_bytes:
        metrics.inc_counter(
            f"collective.axis.{axis_name}.sparse.bytes", int(nbytes)
        )
        metrics.inc_counter(
            f"collective.axis.{axis_name}.sparse.dense_equiv_bytes",
            int(dense_equiv_bytes),
        )
        metrics.set_gauge(
            f"collective.sparse_ratio.{axis_name}",
            metrics.get_counter(f"collective.axis.{axis_name}.sparse.bytes")
            / max(
                metrics.get_counter(
                    f"collective.axis.{axis_name}.sparse.dense_equiv_bytes"
                ),
                1,
            ),
        )
    tracing.account_collective(
        op,
        nbytes,
        chunks if chunks is not None else len(leaves),
        axis_name,
        dense_equiv_bytes=dense_equiv_bytes,
    )


def axis_wire_bytes(snapshot_delta: dict = None) -> Dict[str, int]:
    """Per-axis collective wire bytes from the (delta) metrics counters:
    {axis: bytes}. Pass a `metrics.snapshot_delta` to scope to one entry;
    defaults to the live registry."""
    counters = (
        snapshot_delta.get("counters", {})
        if snapshot_delta is not None
        else metrics.snapshot()["counters"]
    )
    out: Dict[str, int] = {}
    for name, value in counters.items():
        parts = name.split(".")
        if len(parts) == 4 and parts[:2] == ["collective", "axis"] and parts[3] == "bytes":
            out[parts[2]] = int(value)
    return out


def axis_size(axis_name: str = DATA_AXIS) -> int:
    """Static participant count of a mapped axis, as a Python int (legal
    only inside shard_map/pmap tracing). pre-graft jax lacks lax.axis_size;
    psum of the constant 1 folds to the static size on both versions."""
    if hasattr(lax, "axis_size"):
        # tpulint: disable=host-sync-leak -- static mapped-axis size, folded at trace time; no device value crosses
        return int(lax.axis_size(axis_name))
    # tpulint: disable=host-sync-leak -- psum of the constant 1 folds to the static axis size at trace time
    return int(lax.psum(1, axis_name))


def all_reduce_sum(x, axis_name: str = DATA_AXIS):
    """MPI-style all-reduce-sum: each participant gets the global sum.

    Replaces DataStreamUtils.allReduceSum (AllReduceImpl.java:71) as one
    monolithic hardware collective; `all_reduce_sum_chunked` below is the
    decomposed equivalent of the reference's 32KB chunk loop.
    """
    _account("psum", x, axis_name)
    return lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str = DATA_AXIS):
    _account("pmean", x, axis_name)
    return lax.pmean(x, axis_name)


def all_reduce_max(x, axis_name: str = DATA_AXIS):
    _account("pmax", x, axis_name)
    return lax.pmax(x, axis_name)


def all_reduce_min(x, axis_name: str = DATA_AXIS):
    _account("pmin", x, axis_name)
    return lax.pmin(x, axis_name)


def all_gather(x, axis_name: str = DATA_AXIS, axis: int = 0, tiled: bool = True):
    """Gather shards onto every participant — the analogue of broadcast-
    collecting a distributed result (e.g. countWindowAll funnel + rebroadcast,
    KMeans.java:168-173, without the parallelism-1 funnel bottleneck)."""
    _account("all_gather", x, axis_name)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str = DATA_AXIS, scatter_dimension: int = 0):
    _account("psum_scatter", x, axis_name)
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def ppermute_ring(x, axis_name: str = DATA_AXIS, shift: int = 1):
    """Ring shift along an axis — building block for ring pipelines
    (ring attention / pipelined all-reduce patterns)."""
    _account("ppermute", x, axis_name)
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


# ---------------------------------------------------------------------------
# bucketed / ring-pipelined all-reduce (the chunked-AllReduceImpl analogue)
# ---------------------------------------------------------------------------


def _reduce_bucket_rs_ag(vec, axis_name: str, n: int):
    """One bucket via reduce_scatter + all_gather — the bandwidth-optimal
    decomposition (each element crosses each link ~2(n-1)/n times). The
    bucket is zero-padded to an n-divisible length for the tiled scatter;
    padding reduces to zero and is sliced off. Elementwise this computes
    exactly what `psum` computes (same participant set, same per-element
    reduction), so the result is bit-identical to the monolithic op."""
    m = vec.shape[0]
    pad = (-m) % n
    if pad:
        vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
    shard = lax.psum_scatter(vec, axis_name, scatter_dimension=0, tiled=True)
    out = lax.all_gather(shard, axis_name, axis=0, tiled=True)
    return out[:m] if pad else out


def _reduce_bucket_ring(vec, axis_name: str, n: int):
    """One bucket via the ring pipeline: n-1 `ppermute` hops rotate every
    shard's contribution around the ring, and each shard folds the arrivals
    IN REPLICA ORDER (0..n-1 left-associated — the order the backend's own
    all-reduce uses, so the fold stays bit-identical to `psum`; a classic
    rotation-order ring reassociates the sum and is not). With several
    buckets in flight, bucket i+1's hops are dataflow-independent of bucket
    i's fold — the double-buffered schedule where chunk i+1's transfer
    overlaps chunk i's compute (the async-collective pass materializes the
    overlap on hardware)."""
    idx = lax.axis_index(axis_name)
    received = [vec]  # received[s] = contribution of replica (idx - s) mod n
    cur = vec
    for _ in range(n - 1):
        cur = lax.ppermute(cur, axis_name, [(i, (i + 1) % n) for i in range(n)])
        received.append(cur)
    stacked = jnp.stack(received)  # (n, m)
    # contribution of replica r sits at arrival slot (idx - r) mod n
    acc = stacked[jnp.mod(idx - 0, n)]
    for r in range(1, n):
        acc = acc + stacked[jnp.mod(idx - r, n)]
    return acc


def _bucket_sizes(total: int, itemsize: int, chunk_bytes) -> list:
    """Split `total` elements into size-targeted bucket lengths."""
    if not chunk_bytes or chunk_bytes <= 0:
        return [total] if total else []
    per = max(1, int(chunk_bytes) // max(1, itemsize))
    sizes = []
    off = 0
    while off < total:
        sizes.append(min(per, total - off))
        off += sizes[-1]
    return sizes


def all_reduce_sum_chunked(
    x,
    axis_name: str = DATA_AXIS,
    chunk_bytes: int = None,
    ring: bool = None,
):
    """Bucketed all-reduce-sum of a pytree: bit-identical to `lax.psum(x)`.

    The decomposition the reference hand-rolls at 32KB per chunk
    (AllReduceImpl.java:56-103), rebuilt for ICI: leaves are grouped by
    dtype, flattened, and split into `chunk_bytes`-targeted buckets
    (config.collective_chunk_bytes when None, default 4MB); each bucket is
    reduced independently — reduce_scatter+all_gather by default, or the
    ring-pipelined ppermute fold with `ring=True`
    (config.collective_ring when None). Because the per-element reduction
    is unchanged, chunking changes *when bytes move*, never the result;
    the parity suite pins bit-identity for chunk_bytes ∈ {1KB, 32KB, ∞}
    on 1/2/8-shard meshes.
    """
    from .. import config

    chunk_bytes = config.resolve_chunk_bytes(chunk_bytes)
    if ring is None:
        ring = config.collective_ring
    n = axis_size(axis_name)

    leaves, treedef = jax.tree_util.tree_flatten(x)
    if not leaves:
        return x
    if n == 1:
        _account("chunked", x, axis_name, chunks=len(leaves))
        return x

    # group leaves by dtype so buckets stay homogeneous
    by_dtype: Dict[Any, list] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)

    reduce_bucket = _reduce_bucket_ring if ring else _reduce_bucket_rs_ag
    out_leaves = list(leaves)
    num_buckets = 0
    for dtype, idxs in by_dtype.items():
        flat = jnp.concatenate([jnp.ravel(leaves[i]) for i in idxs])
        sizes = _bucket_sizes(flat.shape[0], dtype.itemsize, chunk_bytes)
        num_buckets += len(sizes)
        reduced, off = [], 0
        for size in sizes:
            reduced.append(reduce_bucket(flat[off : off + size], axis_name, n))
            off += size
        flat_red = reduced[0] if len(reduced) == 1 else jnp.concatenate(reduced)
        off = 0
        for i in idxs:
            count = int(np.prod(leaves[i].shape))
            out_leaves[i] = flat_red[off : off + count].reshape(leaves[i].shape)
            off += count
    _account("chunked", x, axis_name, chunks=num_buckets)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


# ---------------------------------------------------------------------------
# sparse index-value all-reduce (SparCML, arXiv:1802.08021)
# ---------------------------------------------------------------------------


def sparse_all_reduce_sum(
    indices,
    values,
    dim: int,
    axis_name: str = DATA_AXIS,
):
    """All-reduce a gradient carried as per-shard (index, value) pairs;
    returns the dense `(dim,)` sum, bit-identical to
    `psum(zeros(dim).at[indices].add(values))` of the densified operand.

    Wire bytes are the pairs, not the dim: each shard contributes its
    `nnz_local * (4 + itemsize)` pair bytes to one all_gather, and the
    dense vector never crosses a link — the SparCML index-value exchange
    that makes sparseWideLR gradient traffic scale with nnz instead of
    dim. The cross-shard combine scatters each shard's gathered pairs into
    its own dense partial and folds the partials in replica order — the
    exact association of the dense path (per-shard sequential scatter-add,
    then replica-ordered psum), which is what makes the result bitwise
    equal, not merely close.

    Out-of-range / negative indices are dropped (`mode="drop"`), matching
    the padded-CSR convention of ops/losses.py. Callers pick sparse vs
    dense at trace time via `sparse_reduce_wins` below.
    """
    n = axis_size(axis_name)
    indices = jnp.ravel(indices)
    values = jnp.ravel(values)
    itemsize = values.dtype.itemsize
    _account(
        "sparse_allreduce",
        (indices, values),
        axis_name,
        chunks=1,
        dense_equiv_bytes=int(dim) * itemsize,
    )
    if n == 1:
        return jnp.zeros((dim,), values.dtype).at[indices].add(values, mode="drop")
    gi = lax.all_gather(indices, axis_name, axis=0, tiled=False)  # (n, m)
    gv = lax.all_gather(values, axis_name, axis=0, tiled=False)

    def scatter_partial(r):
        return jnp.zeros((dim,), values.dtype).at[gi[r]].add(gv[r], mode="drop")

    acc = scatter_partial(0)
    for r in range(1, n):
        acc = acc + scatter_partial(r)
    return acc


def sparse_reduce_wins(
    nnz_local: int, dim: int, itemsize: int = 4, threshold: float = None
) -> bool:
    """Trace-time decision: use the index-value reduction when its
    per-shard pair bytes are at most `threshold` × the dense psum payload
    (config.collective_sparse_threshold when None). Static shapes only —
    the choice is baked into the compiled program."""
    from .. import config

    if threshold is None:
        threshold = config.collective_sparse_threshold
    pair_bytes = int(nnz_local) * (4 + int(itemsize))
    return pair_bytes <= threshold * int(dim) * int(itemsize)


def axis_index(axis_name: str = DATA_AXIS):
    return lax.axis_index(axis_name)


def shard_map_over(mesh: Mesh, in_specs, out_specs, fn=None, check_vma: bool = False):
    """Decorator: run `fn` SPMD over `mesh` with explicit per-shard code.

    The moral equivalent of the reference's per-subtask operator functions;
    collectives above are legal inside.
    """

    def wrap(f):
        if hasattr(jax, "shard_map"):
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
            )
        # pre-graft jax (< 0.6): shard_map lives under experimental with
        # check_rep instead of check_vma
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )

    return wrap(fn) if fn is not None else wrap


# One jitted reducer per (mesh, stacked shape, dtype): defining the jit
# inside host_all_reduce_sum built a fresh closure per call, so jax's
# executable cache (keyed on function identity) missed every time and every
# call RECOMPILED — ~10ms of XLA work per reduce on the host-driven loops.
_HOST_REDUCE_CACHE: Dict[Tuple, Callable] = {}


def _host_reduce_fn(mesh: Mesh, shape: Tuple[int, ...], dtype) -> Callable:
    key = (mesh, tuple(shape), np.dtype(dtype).str)
    fn = _HOST_REDUCE_CACHE.get(key)
    if fn is None:
        sharding = NamedSharding(mesh, P())

        def _sum(stacked):
            return jnp.sum(stacked, axis=0)

        # tpulint: disable=retrace-hazard -- cached in _HOST_REDUCE_CACHE keyed (mesh, shape, dtype); compile count pinned by test_collective_chunks
        fn = jax.jit(_sum, out_shardings=sharding)
        _HOST_REDUCE_CACHE[key] = fn
    return fn


def host_all_reduce_sum(mesh: Mesh, xs):
    """Sum per-shard host arrays into one replicated device array.

    Host-driven (unbounded) loops accumulate per-data-shard partials on host
    (the analogue of the reference's per-subtask accumulators funneled through
    countWindowAll, OnlineKMeans.java pattern); this reduces them with one
    device-side tree-sum and publishes the result replicated over `mesh`.
    The reducer is cached per (mesh, shape, dtype) — repeated reduces of the
    same shape re-enter the same compiled executable (compile-count pinned
    by tests/test_collective_chunks.py)."""
    # host-driven (not inside a trace): this span measures the real
    # per-call stack+upload+reduce wall time
    with tracing.span("collective.host_all_reduce_sum", category="collective") as sp:
        stacked = jnp.stack([jnp.asarray(x) for x in xs])
        sp.set_attr("bytes", int(stacked.size * stacked.dtype.itemsize))
        sp.set_attr("chunks", len(xs))
        metrics.inc_counter("collective.host_all_reduce_sum.calls")
        metrics.inc_counter(
            "collective.host_all_reduce_sum.bytes",
            int(stacked.size * stacked.dtype.itemsize),
        )
        return _host_reduce_fn(mesh, stacked.shape, stacked.dtype)(stacked)
