"""Distributed communication primitives.

The reference builds three comm primitives on Flink's netty shuffle
(SURVEY.md §5): a chunked emulated all-reduce
(common/datastream/AllReduceImpl.java:56-103, 32KB chunks over two
partitionCustom shuffles), broadcast variables (BroadcastUtils.java:64),
and the statefun in-JVM feedback channel (operator/TailOperator.java:76-79).
On TPU these are hardware collectives over ICI; this module is deliberately
tiny — `psum` IS the all-reduce, replication IS the broadcast, and the
feedback edge is a `lax.while_loop` carry (see parallel/iteration.py).

These wrappers are used inside `shard_map`-ped functions; outside
`shard_map`, prefer sharding annotations and let XLA insert collectives.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import tracing
from ..utils import metrics
from .mesh import DATA_AXIS


def _account(op: str, x, axis_name: str) -> None:
    """Record one collective call: op, per-participant payload bytes and
    chunk (pytree-leaf) count. These wrappers run INSIDE jitted/shard_map
    code, so this fires at TRACE time — once per compiled program, not per
    execution — which is exactly when the op's shape is known; the
    counters answer "what collective traffic does this program dispatch",
    the device profile answers how long it took."""
    try:
        leaves = jax.tree_util.tree_leaves(x)
        nbytes = sum(
            int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            for leaf in leaves
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
        )
    except Exception:
        leaves, nbytes = [x], 0
    metrics.inc_counter(f"collective.{op}.calls")
    metrics.inc_counter(f"collective.{op}.bytes", nbytes)
    if tracing.enabled():
        tracing.event(
            f"collective.{op}",
            category="collective",
            bytes=nbytes,
            chunks=len(leaves),
            axis=axis_name,
        )


def all_reduce_sum(x, axis_name: str = DATA_AXIS):
    """MPI-style all-reduce-sum: each participant gets the global sum.

    Replaces DataStreamUtils.allReduceSum (AllReduceImpl.java:71): the
    scatter-reduce/all-gather chunking the reference hand-rolls is what the
    ICI hardware reduction does natively.
    """
    _account("psum", x, axis_name)
    return lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str = DATA_AXIS):
    _account("pmean", x, axis_name)
    return lax.pmean(x, axis_name)


def all_reduce_max(x, axis_name: str = DATA_AXIS):
    _account("pmax", x, axis_name)
    return lax.pmax(x, axis_name)


def all_reduce_min(x, axis_name: str = DATA_AXIS):
    _account("pmin", x, axis_name)
    return lax.pmin(x, axis_name)


def all_gather(x, axis_name: str = DATA_AXIS, axis: int = 0, tiled: bool = True):
    """Gather shards onto every participant — the analogue of broadcast-
    collecting a distributed result (e.g. countWindowAll funnel + rebroadcast,
    KMeans.java:168-173, without the parallelism-1 funnel bottleneck)."""
    _account("all_gather", x, axis_name)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str = DATA_AXIS, scatter_dimension: int = 0):
    _account("psum_scatter", x, axis_name)
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def ppermute_ring(x, axis_name: str = DATA_AXIS, shift: int = 1):
    """Ring shift along an axis — building block for ring pipelines
    (ring attention / pipelined all-reduce patterns)."""
    _account("ppermute", x, axis_name)
    # pre-graft jax lacks lax.axis_size; psum of the constant 1 folds to the
    # static axis size at trace time on both versions
    n = lax.axis_size(axis_name) if hasattr(lax, "axis_size") else lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str = DATA_AXIS):
    return lax.axis_index(axis_name)


def shard_map_over(mesh: Mesh, in_specs, out_specs, fn=None, check_vma: bool = False):
    """Decorator: run `fn` SPMD over `mesh` with explicit per-shard code.

    The moral equivalent of the reference's per-subtask operator functions;
    collectives above are legal inside.
    """

    def wrap(f):
        if hasattr(jax, "shard_map"):
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
            )
        # pre-graft jax (< 0.6): shard_map lives under experimental with
        # check_rep instead of check_vma
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )

    return wrap(fn) if fn is not None else wrap


def host_all_reduce_sum(mesh: Mesh, xs):
    """Sum per-shard host arrays into one replicated device array.

    Host-driven (unbounded) loops accumulate per-data-shard partials on host
    (the analogue of the reference's per-subtask accumulators funneled through
    countWindowAll, OnlineKMeans.java pattern); this reduces them with one
    device-side tree-sum and publishes the result replicated over `mesh`.
    """
    sharding = NamedSharding(mesh, P())

    @partial(jax.jit, out_shardings=sharding)
    def _sum(stacked):
        return jnp.sum(stacked, axis=0)

    # host-driven (not inside a trace): this span measures the real
    # per-call stack+upload+reduce wall time
    with tracing.span("collective.host_all_reduce_sum", category="collective") as sp:
        stacked = jnp.stack([jnp.asarray(x) for x in xs])
        sp.set_attr("bytes", int(stacked.size * stacked.dtype.itemsize))
        sp.set_attr("chunks", len(xs))
        metrics.inc_counter("collective.host_all_reduce_sum.calls")
        metrics.inc_counter(
            "collective.host_all_reduce_sum.bytes",
            int(stacked.size * stacked.dtype.itemsize),
        )
        return _sum(stacked)
