"""Stage persistence: metadata JSON + model-data files.

Keeps the reference's on-disk protocol (flink-ml-core/.../util/
ReadWriteUtils.java): `{path}/metadata` is a JSON object with `className`,
`timestamp`, `paramMap` (param name -> json-encoded value) plus extra
metadata (:98-140); model data lives under `{path}/data` (:440-460);
pipelines store stages under `stages/{idx}` subdirs (:193-246); loading
re-instantiates the class named in metadata and dispatches to its `load`
(:376-410). Java class names from the reference are aliased to our classes
so metadata written by the reference resolves here too.

Model arrays are stored as `.npz` (the reference's per-type binary encoders
become numpy's portable container; there is no JVM to share a wire format
with).
"""

from __future__ import annotations

import importlib
import json
import os
import time
from typing import Any, Dict, Optional

import numpy as np

# Reference Java package -> our module area, e.g.
# org.apache.flink.ml.clustering.kmeans.KMeans -> flink_ml_tpu.models.clustering.kmeans.KMeans
_JAVA_PREFIX = "org.apache.flink.ml."
_PY_PREFIX = "flink_ml_tpu.models."
_PYFLINK_PREFIX = "pyflink.ml.lib."
_CORE_ALIASES = {
    "org.apache.flink.ml.builder.Pipeline": "flink_ml_tpu.pipeline.Pipeline",
    "org.apache.flink.ml.builder.PipelineModel": "flink_ml_tpu.pipeline.PipelineModel",
    "org.apache.flink.ml.builder.Graph": "flink_ml_tpu.graph.Graph",
    "org.apache.flink.ml.builder.GraphModel": "flink_ml_tpu.graph.GraphModel",
    "pyflink.ml.core.builder.Pipeline": "flink_ml_tpu.pipeline.Pipeline",
    "pyflink.ml.core.builder.PipelineModel": "flink_ml_tpu.pipeline.PipelineModel",
}


def _resolve_class_name(class_name: str):
    if class_name in _CORE_ALIASES:
        class_name = _CORE_ALIASES[class_name]
    elif class_name.startswith(_JAVA_PREFIX):
        class_name = _PY_PREFIX + class_name[len(_JAVA_PREFIX):].lower().rsplit(".", 1)[
            0
        ] + "." + class_name.rsplit(".", 1)[1]
    elif class_name.startswith(_PYFLINK_PREFIX):
        class_name = _PY_PREFIX + class_name[len(_PYFLINK_PREFIX):]
    module_name, _, cls_name = class_name.rpartition(".")
    module = importlib.import_module(module_name)
    return getattr(module, cls_name)


def save_metadata(stage, path: str, extra_metadata: Optional[Dict[str, Any]] = None) -> None:
    os.makedirs(path, exist_ok=True)
    metadata: Dict[str, Any] = dict(extra_metadata or {})
    metadata["className"] = f"{type(stage).__module__}.{type(stage).__qualname__}"
    metadata["timestamp"] = int(time.time() * 1000)
    metadata["paramMap"] = {
        p.name: p.json_encode(v) for p, v in stage.get_param_map().items()
    }
    metadata_file = os.path.join(path, "metadata")
    if os.path.exists(metadata_file):
        raise IOError(f"File {metadata_file} already exists")
    with open(metadata_file, "w") as f:
        json.dump(metadata, f)


def load_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "metadata")) as f:
        return json.load(f)


def instantiate_with_params(metadata: Dict[str, Any]):
    """Re-instantiate a stage from metadata (ReadWriteUtils.instantiateWithParams:376)."""
    cls = _resolve_class_name(metadata["className"])
    stage = cls()
    for name, json_value in metadata.get("paramMap", {}).items():
        param = stage.get_param(name)
        if param is None:
            continue  # tolerate params from other versions, as the reference does
        stage.set(param, param.json_decode(json_value))
    return stage


def load_stage(path: str):
    """Load any stage by dispatching on the class named in its metadata
    (ReadWriteUtils.loadStage:410)."""
    metadata = load_metadata(path)
    cls = _resolve_class_name(metadata["className"])
    return cls.load(path)


def get_data_path(path: str) -> str:
    return os.path.join(path, "data")


def save_model_arrays(path: str, name: str = "model_data", **arrays) -> None:
    """Persist model arrays under `{path}/data/{name}.npz`
    (the analogue of ReadWriteUtils.saveModelData:440)."""
    data_dir = get_data_path(path)
    os.makedirs(data_dir, exist_ok=True)
    np.savez(os.path.join(data_dir, name + ".npz"), **{
        k: np.asarray(v) for k, v in arrays.items()
    })


def load_model_arrays(path: str, name: str = "model_data") -> Dict[str, np.ndarray]:
    """Restore model arrays saved by `save_model_arrays`
    (analogue of ReadWriteUtils.loadModelData:460)."""
    with np.load(os.path.join(get_data_path(path), name + ".npz"), allow_pickle=True) as f:
        return {k: f[k] for k in f.files}


def load_arrays_or_reference(path: str, reference_decoder, name: str = "model_data"):
    """Model-data loading shared by every model's `_load_extra`: the native
    npz container when present, else `reference_decoder(path)` for a
    reference-written binary directory (utils/javacodec.py), else a
    FileNotFoundError naming both accepted formats."""
    if model_data_exists(path, name):
        return load_model_arrays(path, name)
    decoded = reference_decoder(path)
    if decoded is None:
        raise FileNotFoundError(
            f"No model data under {get_data_path(path)}: neither the native "
            "npz container nor reference-format binary part files"
        )
    return decoded


def model_data_exists(path: str, name: str = "model_data") -> bool:
    return os.path.exists(os.path.join(get_data_path(path), name + ".npz"))


def get_path_for_pipeline_stage(index: int, num_stages: int, path: str) -> str:
    """`stages/{zero-padded idx}` layout, padded to len(str(numStages))
    exactly as the reference does (ReadWriteUtils.java:193-198:
    format "stages/%0{len(str(numStages))}d") so directories cross-load."""
    width = len(str(num_stages))
    return os.path.join(path, "stages", str(index).zfill(width))


def resolve_pipeline_stage_path(index: int, num_stages: int, path: str) -> str:
    """Stage dir for loading: the reference-width name, falling back to the
    legacy 5-wide padding this framework wrote before aligning."""
    primary = get_path_for_pipeline_stage(index, num_stages, path)
    if os.path.isdir(primary):
        return primary
    legacy = os.path.join(
        path, "stages", str(index).zfill(max(len(str(num_stages - 1)), 5))
    )
    if os.path.isdir(legacy):
        return legacy
    return primary
