"""Guava-compatible MurmurHash3 (32-bit, seed 0) for the hashing trick.

The reference hashes terms with guava's murmur3_32(0)
(feature/hashingtf/HashingTF.java:45,60-61,160-185: hashUnencodedChars for
String, hashInt/hashLong for numerics). Re-implemented from the public
MurmurHash3 spec so hashed feature indices match the reference exactly.
"""

from __future__ import annotations

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M = 0xFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M


def _mix_k1(k1: int) -> int:
    k1 = (k1 * _C1) & _M
    k1 = _rotl(k1, 15)
    return (k1 * _C2) & _M


def _mix_h1(h1: int, k1: int) -> int:
    h1 ^= k1
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _M


def _fmix(h1: int, length: int) -> int:
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M
    h1 ^= h1 >> 16
    return h1


def _to_signed(x: int) -> int:
    return x - (1 << 32) if x >= (1 << 31) else x


def murmur3_hash_int(value: int, seed: int = 0) -> int:
    """guava Murmur3_32.hashInt: one 4-byte block."""
    h1 = _mix_h1(seed & _M, _mix_k1(value & _M))
    return _to_signed(_fmix(h1, 4))


def murmur3_hash_long(value: int, seed: int = 0) -> int:
    """guava Murmur3_32.hashLong: low int then high int."""
    value &= 0xFFFFFFFFFFFFFFFF
    low = value & _M
    high = (value >> 32) & _M
    h1 = _mix_h1(seed & _M, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _to_signed(_fmix(h1, 8))


def murmur3_hash_unencoded_chars(s: str, seed: int = 0) -> int:
    """guava Murmur3_32.hashUnencodedChars: UTF-16 code units, 2 per block."""
    # Java strings are UTF-16: astral chars must become surrogate pairs.
    units = []
    for c in s:
        cp = ord(c)
        if cp > 0xFFFF:
            cp -= 0x10000
            units.append(0xD800 + (cp >> 10))
            units.append(0xDC00 + (cp & 0x3FF))
        else:
            units.append(cp)
    h1 = seed & _M
    for i in range(0, len(units) - 1, 2):
        k1 = units[i] | (units[i + 1] << 16)
        h1 = _mix_h1(h1, _mix_k1(k1))
    if len(units) % 2 == 1:
        h1 ^= _mix_k1(units[-1])
    return _to_signed(_fmix(h1, 2 * len(units)))


def hash_term(obj, seed: int = 0) -> int:
    """Dispatch by type like HashingTF.hash (HashingTF.java:160-185)."""
    import struct

    if obj is None:
        return 0
    if isinstance(obj, bool):
        return murmur3_hash_int(1 if obj else 0, seed)
    if isinstance(obj, int):
        if -(2**31) <= obj < 2**31:
            return murmur3_hash_int(obj, seed)
        return murmur3_hash_long(obj, seed)
    if isinstance(obj, float):
        bits = struct.unpack("<q", struct.pack("<d", obj))[0]
        return murmur3_hash_long(bits, seed)
    if isinstance(obj, str):
        return murmur3_hash_unencoded_chars(obj, seed)
    raise TypeError(f"Unsupported term type {type(obj).__name__} for hashing")
