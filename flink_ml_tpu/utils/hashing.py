"""Guava-compatible MurmurHash3 (32-bit, seed 0) for the hashing trick.

The reference hashes terms with guava's murmur3_32(0)
(feature/hashingtf/HashingTF.java:45,60-61,160-185: hashUnencodedChars for
String, hashInt/hashLong for numerics). Re-implemented from the public
MurmurHash3 spec so hashed feature indices match the reference exactly.
"""

from __future__ import annotations

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M = 0xFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M


def _mix_k1(k1: int) -> int:
    k1 = (k1 * _C1) & _M
    k1 = _rotl(k1, 15)
    return (k1 * _C2) & _M


def _mix_h1(h1: int, k1: int) -> int:
    h1 ^= k1
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _M


def _fmix(h1: int, length: int) -> int:
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M
    h1 ^= h1 >> 16
    return h1


def _to_signed(x: int) -> int:
    return x - (1 << 32) if x >= (1 << 31) else x


def murmur3_hash_int(value: int, seed: int = 0) -> int:
    """guava Murmur3_32.hashInt: one 4-byte block."""
    h1 = _mix_h1(seed & _M, _mix_k1(value & _M))
    return _to_signed(_fmix(h1, 4))


def murmur3_hash_long(value: int, seed: int = 0) -> int:
    """guava Murmur3_32.hashLong: low int then high int."""
    value &= 0xFFFFFFFFFFFFFFFF
    low = value & _M
    high = (value >> 32) & _M
    h1 = _mix_h1(seed & _M, _mix_k1(low))
    h1 = _mix_h1(h1, _mix_k1(high))
    return _to_signed(_fmix(h1, 8))


def murmur3_hash_unencoded_chars(s: str, seed: int = 0) -> int:
    """guava Murmur3_32.hashUnencodedChars: UTF-16 code units, 2 per block."""
    # Java strings are UTF-16: astral chars must become surrogate pairs.
    units = []
    for c in s:
        cp = ord(c)
        if cp > 0xFFFF:
            cp -= 0x10000
            units.append(0xD800 + (cp >> 10))
            units.append(0xDC00 + (cp & 0x3FF))
        else:
            units.append(cp)
    h1 = seed & _M
    for i in range(0, len(units) - 1, 2):
        k1 = units[i] | (units[i + 1] << 16)
        h1 = _mix_h1(h1, _mix_k1(k1))
    if len(units) % 2 == 1:
        h1 ^= _mix_k1(units[-1])
    return _to_signed(_fmix(h1, 2 * len(units)))


def hash_term(obj, seed: int = 0) -> int:
    """Dispatch by type like HashingTF.hash (HashingTF.java:160-185)."""
    import struct

    if obj is None:
        return 0
    if isinstance(obj, bool):
        return murmur3_hash_int(1 if obj else 0, seed)
    if isinstance(obj, int):
        if -(2**31) <= obj < 2**31:
            return murmur3_hash_int(obj, seed)
        return murmur3_hash_long(obj, seed)
    if isinstance(obj, float):
        bits = struct.unpack("<q", struct.pack("<d", obj))[0]
        return murmur3_hash_long(bits, seed)
    if isinstance(obj, str):
        return murmur3_hash_unencoded_chars(obj, seed)
    raise TypeError(f"Unsupported term type {type(obj).__name__} for hashing")


def murmur3_batch_unencoded_chars(strings, seed: int = 0):
    """Vectorized guava Murmur3_32.hashUnencodedChars over a unicode array.

    Operates on numpy fixed-width unicode (UTF-32 view = UTF-16 code units
    for BMP text, which covers the ASCII `col=value` strings FeatureHasher
    produces); strings containing astral characters fall back to the scalar
    path. Arithmetic runs in uint64 with explicit 32-bit masking — a Python
    per-string loop over the benchmark's 30M strings is minutes on this
    single-core host, this is a few vector passes.
    Returns signed int32 hashes identical to `murmur3_hash_unencoded_chars`.
    """
    import numpy as np

    S = np.asarray(strings)
    if S.dtype.kind != "U":
        was_object = S.dtype == object
        S = S.astype(str)
        if was_object:
            # numpy U storage strips TRAILING U+0000, so such strings can't
            # round-trip the vectorized layout (Java hashes them). Detect
            # via python len (O(1) per string, no char scan) vs the stored
            # width and hash per-row if any row lost characters. Non-str
            # objects render via str() and can't contain NULs.
            src = np.asarray(strings, dtype=object)
            py_lens = np.fromiter(
                (len(s) if isinstance(s, str) else -1 for s in src),
                np.int64,
                count=len(src),
            )
            if (py_lens > np.char.str_len(S)).any():
                return np.asarray(
                    [murmur3_hash_unencoded_chars(str(s), seed) for s in src],
                    np.int64,
                )
    n = S.shape[0]
    M = S.dtype.itemsize // 4
    if M == 0:
        return np.full(n, _to_signed(_fmix(seed & _M, 0)), np.int64)
    U = np.ascontiguousarray(S).view(np.uint32).reshape(n, M).astype(np.uint64)
    if (U > 0xFFFF).any():  # astral chars need surrogate-pair splitting
        return np.asarray(
            [murmur3_hash_unencoded_chars(str(s), seed) for s in S], np.int64
        )
    # length = last nonzero + 1: zeros BEFORE it are real embedded U+0000
    # characters (Java hashes them); numpy cannot represent trailing ones.
    nz = U != 0
    lens = (M - np.argmax(nz[:, ::-1], axis=1)).astype(np.int64)
    lens[~nz.any(axis=1)] = 0

    MASK = np.uint64(_M)

    def rotl(x, r):
        return ((x << np.uint64(r)) | (x >> np.uint64(32 - r))) & MASK

    def mix_k1(k1):
        k1 = (k1 * np.uint64(_C1)) & MASK
        k1 = rotl(k1, 15)
        return (k1 * np.uint64(_C2)) & MASK

    def mix_h1(h1, k1):
        h1 = h1 ^ k1
        h1 = rotl(h1, 13)
        return (h1 * np.uint64(5) + np.uint64(0xE6546B64)) & MASK

    h1 = np.full(n, seed & _M, np.uint64)
    nblocks = lens // 2
    for b in range(M // 2):
        k1 = (U[:, 2 * b] | (U[:, 2 * b + 1] << np.uint64(16))) & MASK
        h1 = np.where(b < nblocks, mix_h1(h1, mix_k1(k1)), h1)
    odd = (lens % 2) == 1
    last = U[np.arange(n), np.maximum(lens - 1, 0)]
    h1 = np.where(odd, h1 ^ mix_k1(last), h1)

    h1 = h1 ^ (np.uint64(2) * lens.astype(np.uint64))
    h1 = (h1 ^ (h1 >> np.uint64(16))) & MASK
    h1 = (h1 * np.uint64(0x85EBCA6B)) & MASK
    h1 = (h1 ^ (h1 >> np.uint64(13))) & MASK
    h1 = (h1 * np.uint64(0xC2B2AE35)) & MASK
    h1 = (h1 ^ (h1 >> np.uint64(16))) & MASK
    out = h1.astype(np.int64)
    return np.where(out >= 2**31, out - 2**32, out)
