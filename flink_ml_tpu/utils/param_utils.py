"""Param plumbing helpers (reference: util/ParamUtils.java:89)."""

from __future__ import annotations


def update_existing_params(dst, src) -> None:
    """Copy every param value from `src` to `dst` for params `dst` defines
    (ParamUtils.updateExistingParams) — used by estimators to hand their
    shared params to the fitted model."""
    for param, value in src.get_param_map().items():
        dst_param = dst.get_param(param.name)
        if dst_param is not None:
            dst.set(dst_param, value)
