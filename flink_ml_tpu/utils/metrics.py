"""Process-wide metrics + profiling surface.

The reference delegates observability to the Flink web UI, slf4j, and
per-operator metric groups — its only custom metric is the online models'
`modelDataVersion` gauge (OnlineKMeansModel.java:161-166,
OnlineLogisticRegressionModel.java:133); the benchmark module adds
wall-clock/throughput accounting (BenchmarkUtils.java:131-144). The
TPU-native equivalents here:

- `timed(name)` — accumulate wall-clock spans per named phase (the
  benchmark runner times datagen/fit/transform/collect; the iteration
  runtime times epochs);
- `set_gauge`/`inc_counter` — the metric-group analogue (online models
  publish modelDataVersion here);
- `profile_trace(dir)` — a `jax.profiler` trace scope producing
  TensorBoard-loadable device profiles (SURVEY.md §5 called for this
  "from day one").

Everything is a plain module-level registry: `snapshot()` returns a copy,
`reset()` clears — cheap enough to stay always-on.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List

_timers: Dict[str, List[float]] = {}
_gauges: Dict[str, float] = {}
_counters: Dict[str, int] = {}


@contextmanager
def timed(name: str):
    """Accumulate the wall-clock duration of this block under `name`."""
    start = time.perf_counter()
    try:
        yield
    finally:
        _timers.setdefault(name, []).append(time.perf_counter() - start)


def record_time(name: str, seconds: float) -> None:
    _timers.setdefault(name, []).append(seconds)


def set_gauge(name: str, value: float) -> None:
    _gauges[name] = value


def get_gauge(name: str, default=None):
    return _gauges.get(name, default)


def inc_counter(name: str, delta: int = 1) -> None:
    _counters[name] = _counters.get(name, 0) + delta


def get_counter(name: str, default: int = 0) -> int:
    return _counters.get(name, default)


def timer_totals() -> Dict[str, float]:
    """Total seconds per phase."""
    return {k: float(sum(v)) for k, v in _timers.items()}


def snapshot() -> Dict[str, Dict]:
    """A copyable view of every metric: per-phase {count, totalMs, lastMs},
    gauges, counters."""
    return {
        "timers": {
            k: {
                "count": len(v),
                "totalMs": sum(v) * 1000.0,
                "lastMs": v[-1] * 1000.0,
            }
            for k, v in _timers.items()
        },
        "gauges": dict(_gauges),
        "counters": dict(_counters),
    }


def snapshot_delta(before: Dict[str, Dict], after: Dict[str, Dict]) -> Dict[str, Dict]:
    """The registry activity between two `snapshot()` calls: timer and
    counter increments (entries that did not move are dropped), gauges as
    of `after`. The benchmark runner embeds this per entry so every BENCH
    json carries its own span/readback/compile evidence."""
    timers = {}
    for name, stats in after["timers"].items():
        prev = before["timers"].get(name, {"count": 0, "totalMs": 0.0})
        count = stats["count"] - prev["count"]
        if count:
            timers[name] = {
                "count": count,
                "totalMs": stats["totalMs"] - prev["totalMs"],
                "lastMs": stats["lastMs"],
            }
    counters = {}
    for name, value in after["counters"].items():
        delta = value - before["counters"].get(name, 0)
        if delta:
            counters[name] = delta
    return {"timers": timers, "gauges": dict(after["gauges"]), "counters": counters}


def reset() -> None:
    _timers.clear()
    _gauges.clear()
    _counters.clear()


@contextmanager
def profile_trace(log_dir: str):
    """Capture a jax.profiler device trace for this block (view with
    TensorBoard's profile plugin). No-op overhead when not used."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
