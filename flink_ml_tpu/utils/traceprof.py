"""Profiler-trace capture + analysis for benchmark evidence.

Runs a callable under ``jax.profiler.trace`` and reduces the emitted
chrome-format trace (``*.trace.json.gz``) to the numbers perf work needs:
device-busy time, HBM bytes actually accessed, model FLOPs executed, and a
per-HLO-category breakdown. This replaces the flop-model MFU in bench.py
with measurements from the device timeline — the reference's benchmark
harness times whole jobs (BenchmarkUtils.java:131-144) and cannot see
inside them; here the trace separates device compute from the host/tunnel
dispatch+readback wall that dominates small jobs.

No tensorboard/tensorflow dependency: the trace.json.gz the profiler
writes alongside the xplane.pb is parsed directly with gzip+json.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
import time
from typing import Any, Callable, Dict, Optional


def capture_trace(fn: Callable[[], Any], trace_dir: Optional[str] = None) -> Dict:
    """Run ``fn`` under the JAX profiler; return ``analyze_trace`` of the
    newest trace plus the traced call's host wall time."""
    import jax

    trace_dir = trace_dir or tempfile.mkdtemp(prefix="flink_ml_tpu_trace_")
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        fn()
    wall_s = time.perf_counter() - t0
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json.gz"))
    )
    if not paths:
        return {"error": "no trace written", "wallMs": wall_s * 1000.0}
    stats = analyze_trace(paths[-1])
    stats["wallMs"] = wall_s * 1000.0
    stats["tracePath"] = paths[-1]
    return stats


def analyze_trace(path: str) -> Dict:
    """Reduce a chrome-format JAX profiler trace to device-side totals.

    Device busy time is the sum of "XLA Modules" spans (module executions
    never overlap on a core); bytes/FLOPs come from per-op stats on the
    "XLA Ops" thread (``bytes_accessed`` / ``model_flops``, the stats the
    profiler derives from the HLO cost model against the *executed*
    program)."""
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])

    device_pids = set()
    thread_names: Dict[tuple, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name" and str(
            e.get("args", {}).get("name", "")
        ).startswith("/device:"):
            device_pids.add(e["pid"])
        if e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = e.get("args", {}).get("name", "")

    busy_us = 0.0
    modules = []
    op_bytes = 0
    op_flops = 0
    ops_us = 0.0
    by_category: Dict[str, Dict[str, float]] = {}
    top_ops: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        tname = thread_names.get((e["pid"], e.get("tid")), "")
        dur = float(e.get("dur", 0.0))
        if tname == "XLA Modules":
            busy_us += dur
            modules.append({"name": e.get("name", ""), "durUs": dur})
        elif tname == "XLA Ops":
            args = e.get("args", {}) or {}
            b = int(args.get("bytes_accessed", 0))
            fl = int(args.get("model_flops", 0))
            op_bytes += b
            op_flops += fl
            ops_us += dur
            cat = args.get("hlo_category", "unknown")
            agg = by_category.setdefault(
                cat, {"durUs": 0.0, "bytes": 0, "flops": 0, "count": 0}
            )
            agg["durUs"] += dur
            agg["bytes"] += b
            agg["flops"] += fl
            agg["count"] += 1
            op = top_ops.setdefault(
                e.get("name", ""), {"durUs": 0.0, "bytes": 0, "count": 0}
            )
            op["durUs"] += dur
            op["bytes"] += b
            op["count"] += 1

    busy_s = busy_us / 1e6
    return {
        "deviceBusyMs": busy_us / 1000.0,
        "deviceOpsMs": ops_us / 1000.0,
        "numModuleExecutions": len(modules),
        "hbmBytesAccessed": op_bytes,
        "modelFlops": op_flops,
        "hbmGBps": (op_bytes / busy_s / 1e9) if busy_s > 0 else None,
        "flopsPerSec": (op_flops / busy_s) if busy_s > 0 else None,
        "byCategory": {
            k: v
            for k, v in sorted(
                by_category.items(), key=lambda kv: -kv[1]["durUs"]
            )
        },
        "topOps": {
            k: v
            for k, v in sorted(top_ops.items(), key=lambda kv: -kv[1]["durUs"])[:12]
        },
    }
