"""Lazily-compiled module-level jit kernels.

jax.jit called inside a function body creates a NEW wrapper per call, so
every call recompiles (seconds each over this environment's remote-compile
tunnel). These helpers give the two needed shapes — a singleton kernel and
a kernel family keyed by a static value — as one-liners, replacing the
hand-rolled `global _X_JIT` caches that were spreading per module.

Runtime accounting: each wrapper creation bumps the `jit.kernels` counter,
and the first one installs the obs jax.monitoring hooks, so every actual
XLA backend compile (including shape-driven recompiles of an existing
wrapper) lands in `jit.compiles`/`jit.compile` and — when tracing is on —
as a `category=compile` span (obs/tracing.py)."""

from __future__ import annotations

from typing import Callable, Dict, Tuple


def _account_new_kernel() -> None:
    from ..obs import tracing
    from . import metrics

    metrics.inc_counter("jit.kernels")
    tracing.install_jax_hooks()  # jax is imported by the caller's next line


def lazy_jit(fn: Callable, **jit_kwargs) -> Callable:
    """A callable that jits `fn` on first use and reuses the wrapper."""
    box = []

    def call(*args, **kwargs):
        if not box:
            import jax

            _account_new_kernel()
            box.append(jax.jit(fn, **jit_kwargs))
        return box[0](*args, **kwargs)

    call.__name__ = getattr(fn, "__name__", "lazy_jit")
    return call


def keyed_jit(make_fn: Callable, **jit_kwargs) -> Callable:
    """A factory cache: `keyed_jit(make)(key)` jits `make(key)` once per
    distinct key (for kernels whose body depends on a static value)."""
    cache: Dict[Tuple, Callable] = {}

    def get(*key):
        fn = cache.get(key)
        if fn is None:
            import jax

            _account_new_kernel()
            fn = jax.jit(make_fn(*key), **jit_kwargs)
            cache[key] = fn
        return fn

    return get
