"""Lazily-compiled module-level jit kernels.

jax.jit called inside a function body creates a NEW wrapper per call, so
every call recompiles (seconds each over this environment's remote-compile
tunnel). These helpers give the two needed shapes — a singleton kernel and
a kernel family keyed by a static value — as one-liners, replacing the
hand-rolled `global _X_JIT` caches that were spreading per module.

Runtime accounting: each wrapper creation bumps the `jit.kernels` counter,
and the first one installs the obs jax.monitoring hooks, so every actual
XLA backend compile (including shape-driven recompiles of an existing
wrapper) lands in `jit.compiles`/`jit.compile` and — when tracing is on —
as a `category=compile` span (obs/tracing.py). Every kernel body is
additionally wrapped so each *trace* ticks `jit.traces` (the body only
runs at trace time), giving the serving no-compile SLA an exact
trace count to assert against.

This module is also the AOT program bank's integration funnel
(compilebank.py, docs/performance.md §12): when `config.program_bank_dir`
is set, every call consults the bank before tracing — a hit calls a
warm-loaded serialized executable (no trace, no compile); a miss
AOT-compiles and back-fills the bank. With the bank off (the default)
behavior is byte-for-byte today's path.

`keyed_jit` factory caches are LRU-bounded at `config.kernel_cache_size`
entries (`jit.kernelCacheEvict` counter + `jit.kernelCacheSize` gauge);
an evicted key re-traces on its next touch with identical results.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional, Tuple


def _account_new_kernel() -> None:
    from ..obs import tracing
    from . import metrics

    metrics.inc_counter("jit.kernels")
    tracing.install_jax_hooks()  # jax is imported by the caller's next line


def _kernel_id(fn: Callable, key: Tuple = ()) -> Optional[str]:
    """Process-restart-stable bank identity for a kernel, or None when a
    factory key has no stable token (that family skips the bank)."""
    base = f"{getattr(fn, '__module__', '?')}.{getattr(fn, '__qualname__', getattr(fn, '__name__', '?'))}"
    if not key:
        return base
    from .. import compilebank

    tokens = [compilebank.static_token(k) for k in key]
    if any(t is None for t in tokens):
        return None
    return base + "[" + ",".join(tokens) + "]"


def _traced(fn: Callable) -> Callable:
    """Wrap a kernel body so each trace ticks `jit.traces`: the wrapper
    body only executes while jax is tracing, never on a cache hit."""
    import functools

    from . import metrics

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        metrics.inc_counter("jit.traces")
        return fn(*args, **kwargs)

    return traced


def _bank_consult(kernel_id: Optional[str], traced, args, kwargs, jit_kwargs):
    """(handled, result) through the program bank; (False, None) when the
    bank is off or the call is not bankable."""
    if kernel_id is None:
        return False, None
    from .. import compilebank

    bank = compilebank.active_bank()
    if bank is None:
        return False, None
    return compilebank.banked_call(
        bank, kernel_id, traced, args, kwargs, jit_kwargs
    )


def lazy_jit(fn: Callable, **jit_kwargs) -> Callable:
    """A callable that jits `fn` on first use and reuses the wrapper."""
    box = []

    def call(*args, **kwargs):
        if not box:
            import jax

            _account_new_kernel()
            traced = _traced(fn)
            box.append((traced, jax.jit(traced, **jit_kwargs)))
        traced, jitted = box[0]
        handled, result = _bank_consult(
            _kernel_id(fn), traced, args, kwargs, jit_kwargs
        )
        if handled:
            return result
        return jitted(*args, **kwargs)

    call.__name__ = getattr(fn, "__name__", "lazy_jit")
    return call


def keyed_jit(make_fn: Callable, **jit_kwargs) -> Callable:
    """A factory cache: `keyed_jit(make)(key)` jits `make(key)` once per
    distinct key (for kernels whose body depends on a static value)."""
    cache: "OrderedDict[Tuple, Callable]" = OrderedDict()

    def get(*key):
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
            return fn
        import jax

        from .. import config
        from . import metrics

        _account_new_kernel()
        traced = _traced(make_fn(*key))
        jitted = jax.jit(traced, **jit_kwargs)
        kernel_id = _kernel_id(make_fn, key)

        def call(*args, **kwargs):
            handled, result = _bank_consult(
                kernel_id, traced, args, kwargs, jit_kwargs
            )
            if handled:
                return result
            return jitted(*args, **kwargs)

        call.__name__ = getattr(make_fn, "__name__", "keyed_jit")
        cache[key] = call
        limit = max(1, int(config.kernel_cache_size))
        while len(cache) > limit:
            cache.popitem(last=False)
            metrics.inc_counter("jit.kernelCacheEvict")
        metrics.set_gauge("jit.kernelCacheSize", float(len(cache)))
        return call

    return get
