"""java.util.Random-compatible LCG.

MinHashLSH generates its random hash coefficients with `new Random(seed)` +
`nextInt(bound)` (feature/lsh/MinHashLSHModelData.java:generateModelData),
so model data written by the reference only matches ours if the RNG stream
matches. java.util.Random's algorithm is publicly specified (a 48-bit LCG).
"""

from __future__ import annotations

_MULT = 0x5DEECE66D
_ADD = 0xB
_MASK = (1 << 48) - 1


class JavaRandom:
    def __init__(self, seed: int):
        self._seed = (seed ^ _MULT) & _MASK

    def _next(self, bits: int) -> int:
        self._seed = (self._seed * _MULT + _ADD) & _MASK
        value = self._seed >> (48 - bits)
        # interpret as signed 32-bit when bits == 32
        if bits == 32 and value >= (1 << 31):
            value -= 1 << 32
        return value

    def next_int(self, bound: int = None) -> int:
        if bound is None:
            return self._next(32)
        if bound <= 0:
            raise ValueError("bound must be positive")
        if (bound & -bound) == bound:  # power of two
            return (bound * self._next(31)) >> 31
        while True:
            bits = self._next(31)
            val = bits % bound
            if bits - val + (bound - 1) < (1 << 31):
                return val

    def next_double(self) -> float:
        return ((self._next(26) << 27) + self._next(27)) / float(1 << 53)

    def next_long(self) -> int:
        hi = self._next(32)
        lo = self._next(32)
        # Wrap to signed 64-bit the way Java overflow does (hi =
        # Integer.MIN_VALUE with negative lo would otherwise escape the
        # long range as an unbounded Python int).
        v = ((hi << 32) + lo) & ((1 << 64) - 1)
        return v - (1 << 64) if v >= (1 << 63) else v
