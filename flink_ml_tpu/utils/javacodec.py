"""Binary codecs for model data written by the reference's Java encoders.

The reference persists model data as binary part files under
`{stage_path}/data/`, one encoder per model class
(ReadWriteUtils.saveModelData/loadModelData,
flink-ml-core/.../util/ReadWriteUtils.java:440-460). The wire format is
Java DataOutput (big-endian):

- DenseVector  (linalg/typeinfo/DenseVectorSerializer.java:78-99):
  int32 length + length x float64 values.
- KMeansModelData  (clustering/kmeans/KMeansModelData.java:140-154):
  int32 numCentroids + numCentroids x DenseVector + weights DenseVector.
- LogisticRegressionModelData
  (classification/logisticregression/LogisticRegressionModelData.java:
  110-121): DenseVector coefficient + int64 modelVersion.
- LinearSVCModelData / LinearRegressionModelData mirror the LR layout
  minus the version long (a single DenseVector coefficient).

Every other Estimator model type (NaiveBayes, Knn, StringIndexer, OneHot,
IDF, CountVectorizer, the four scalers, KBins, VectorIndexer, Imputer,
MinHashLSH, the two selectors) has its codec below, composed from the
Flink primitive serializer formats documented mid-file; the full
per-model byte-format table with Java source citations is
docs/model_formats.md.

These codecs let models LOAD reference-written directories (the npz
native format stays the default for save) and write reference-format
fixtures for tests. Encoders/decoders are exact inverses; the committed
fixtures under tests/fixtures/ were produced by the encoders here,
implementing the cited Java formats byte for byte.
"""

from __future__ import annotations

import glob
import io
import os
import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

_INT = struct.Struct(">i")
_LONG = struct.Struct(">q")


def encode_dense_vector(values: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(np.asarray(values, dtype=np.float64))
    return _INT.pack(arr.shape[0]) + arr.astype(">f8").tobytes()


def read_dense_vector(stream: io.BufferedIOBase) -> np.ndarray:
    raw = stream.read(4)
    if len(raw) < 4:
        raise EOFError("end of stream")
    (length,) = _INT.unpack(raw)
    data = stream.read(8 * length)
    if len(data) < 8 * length:
        raise EOFError("truncated DenseVector payload")
    return np.frombuffer(data, dtype=">f8").astype(np.float64)


def encode_kmeans_model_data(centroids: np.ndarray, weights: np.ndarray) -> bytes:
    out = [_INT.pack(int(np.shape(centroids)[0]))]
    for c in np.asarray(centroids, dtype=np.float64):
        out.append(encode_dense_vector(c))
    out.append(encode_dense_vector(weights))
    return b"".join(out)


def read_kmeans_model_data(stream) -> Tuple[np.ndarray, np.ndarray]:
    raw = stream.read(4)
    if len(raw) < 4:
        raise EOFError("end of stream")
    (num,) = _INT.unpack(raw)
    centroids = np.stack([read_dense_vector(stream) for _ in range(num)])
    weights = read_dense_vector(stream)
    return centroids, weights


def encode_logisticregression_model_data(
    coefficient: np.ndarray, model_version: int = 0
) -> bytes:
    return encode_dense_vector(coefficient) + _LONG.pack(int(model_version))


def read_logisticregression_model_data(stream) -> Tuple[np.ndarray, int]:
    coefficient = read_dense_vector(stream)
    raw = stream.read(8)
    if len(raw) < 8:
        raise EOFError("truncated modelVersion")
    (version,) = _LONG.unpack(raw)
    return coefficient, version


def encode_coefficient_model_data(coefficient: np.ndarray) -> bytes:
    """LinearSVCModelData / LinearRegressionModelData: one DenseVector."""
    return encode_dense_vector(coefficient)


# ---------------------------------------------------------------------------
# Flink primitive serializer wire formats
# ---------------------------------------------------------------------------
# The model-data encoders below compose these primitives exactly as the
# reference's ModelDataEncoder classes compose the corresponding Flink
# serializers (all big-endian DataOutput unless noted):
#
# - StringValue.writeString (flink-core StringValue.java): length+1 as a
#   7-bit varint (0 encodes null), then each UTF-16 code unit as a varint.
#   Used by StringSerializer and StringArraySerializer.
# - {Int,Long,Double}PrimitiveArraySerializer: int32 length + N fixed-width
#   big-endian values.
# - MapSerializer: int32 size, then per entry key, then a null flag byte
#   for the value (0x01 = null) followed by the value when present.
# - DenseMatrixSerializer (linalg/typeinfo/DenseMatrixSerializer.java:76-95):
#   int32 numRows + int32 numCols + numRows*numCols float64 column-major.

_HIGH_BIT = 0x80


def _write_varint(out: list, value: int) -> None:
    while value >= _HIGH_BIT:
        out.append(bytes([(value & 0x7F) | _HIGH_BIT]))
        value >>= 7
    out.append(bytes([value]))


def _read_varint(stream) -> int:
    shift, result = 0, 0
    while True:
        raw = stream.read(1)
        if not raw:
            raise EOFError("truncated varint")
        b = raw[0]
        result |= (b & 0x7F) << shift
        if b < _HIGH_BIT:
            return result
        shift += 7


def encode_java_string(s: Optional[str]) -> bytes:
    """StringValue.writeString: None -> 0x00; else varint(len+1) + per-char
    varints of the UTF-16 code units."""
    if s is None:
        return b"\x00"
    units: List[int] = []
    for c in s:
        cp = ord(c)
        if cp > 0xFFFF:  # Java chars are UTF-16 code units
            cp -= 0x10000
            units.append(0xD800 + (cp >> 10))
            units.append(0xDC00 + (cp & 0x3FF))
        else:
            units.append(cp)
    out: List[bytes] = []
    _write_varint(out, len(units) + 1)
    for u in units:
        _write_varint(out, u)
    return b"".join(out)


def read_java_string(stream) -> Optional[str]:
    length = _read_varint(stream)
    if length == 0:
        return None
    units = [_read_varint(stream) for _ in range(length - 1)]
    chars: List[str] = []
    i = 0
    while i < len(units):
        u = units[i]
        if 0xD800 <= u <= 0xDBFF and i + 1 < len(units) and 0xDC00 <= units[i + 1] <= 0xDFFF:
            chars.append(chr(0x10000 + ((u - 0xD800) << 10) + (units[i + 1] - 0xDC00)))
            i += 2
        else:
            chars.append(chr(u))
            i += 1
    return "".join(chars)


def encode_string_array(strings) -> bytes:
    out = [_INT.pack(len(strings))]
    for s in strings:
        out.append(encode_java_string(None if s is None else str(s)))
    return b"".join(out)


def read_string_array(stream) -> List[Optional[str]]:
    (count,) = _INT.unpack(_read_exact(stream, 4))
    return [read_java_string(stream) for _ in range(count)]


def _read_exact(stream, size: int) -> bytes:
    data = stream.read(size)
    if len(data) < size:
        raise EOFError("end of stream")
    return data


def _encode_primitive_array(values, fmt: str) -> bytes:
    arr = np.ascontiguousarray(np.asarray(values))
    return _INT.pack(arr.shape[0]) + arr.astype(fmt).tobytes()


def _read_primitive_array(stream, fmt: str, width: int) -> np.ndarray:
    (length,) = _INT.unpack(_read_exact(stream, 4))
    return np.frombuffer(_read_exact(stream, width * length), dtype=fmt)


def encode_double_array(values) -> bytes:
    return _encode_primitive_array(values, ">f8")


def read_double_array(stream) -> np.ndarray:
    return _read_primitive_array(stream, ">f8", 8).astype(np.float64)


def encode_int_array(values) -> bytes:
    return _encode_primitive_array(values, ">i4")


def read_int_array(stream) -> np.ndarray:
    return _read_primitive_array(stream, ">i4", 4).astype(np.int32)


def encode_long_array(values) -> bytes:
    return _encode_primitive_array(values, ">i8")


def read_long_array(stream) -> np.ndarray:
    return _read_primitive_array(stream, ">i8", 8).astype(np.int64)


_SCALAR_CODECS = {
    "double": (
        lambda v: struct.pack(">d", float(v)),
        lambda s: struct.unpack(">d", _read_exact(s, 8))[0],
    ),
    "int": (
        lambda v: _INT.pack(int(v)),
        lambda s: _INT.unpack(_read_exact(s, 4))[0],
    ),
    "long": (
        lambda v: _LONG.pack(int(v)),
        lambda s: _LONG.unpack(_read_exact(s, 8))[0],
    ),
    "string": (encode_java_string, read_java_string),
}


def encode_java_map(mapping: dict, key_codec: str, value_codec) -> bytes:
    """Flink MapSerializer: size + (key, valueNullFlag, value) entries.
    ``value_codec`` is a codec name or a (encode, read) pair for nesting."""
    k_enc, _ = _SCALAR_CODECS[key_codec]
    v_enc = _SCALAR_CODECS[value_codec][0] if isinstance(value_codec, str) else value_codec[0]
    out = [_INT.pack(len(mapping))]
    for k, v in mapping.items():
        out.append(k_enc(k))
        if v is None:
            out.append(b"\x01")
        else:
            out.append(b"\x00")
            out.append(v_enc(v))
    return b"".join(out)


def read_java_map(stream, key_codec: str, value_codec) -> dict:
    _, k_read = _SCALAR_CODECS[key_codec]
    v_read = _SCALAR_CODECS[value_codec][1] if isinstance(value_codec, str) else value_codec[1]
    (size,) = _INT.unpack(_read_exact(stream, 4))
    result = {}
    for _ in range(size):
        k = k_read(stream)
        null_flag = _read_exact(stream, 1)
        result[k] = None if null_flag == b"\x01" else v_read(stream)
    return result


def encode_dense_matrix(matrix: np.ndarray) -> bytes:
    arr = np.asarray(matrix, dtype=np.float64)
    rows, cols = arr.shape
    # DenseMatrix stores values column-major (DenseMatrix.java:83)
    return _INT.pack(rows) + _INT.pack(cols) + arr.astype(">f8").T.tobytes()


def read_dense_matrix(stream) -> np.ndarray:
    (rows,) = _INT.unpack(_read_exact(stream, 4))
    (cols,) = _INT.unpack(_read_exact(stream, 4))
    flat = np.frombuffer(_read_exact(stream, 8 * rows * cols), dtype=">f8")
    return flat.reshape(cols, rows).T.astype(np.float64)


def _part_sort_key(path: str):
    """Numeric-aware part-file ordering: 'part-0-10' sorts after 'part-0-9'
    (plain lexical order would make records[-1] a stale model once a
    writer produces 10+ parts)."""
    name = os.path.basename(path)
    pieces = name.replace("_", "-").split("-")
    return [int(p) if p.isdigit() else p for p in pieces]


def _data_files(stage_path: str) -> List[str]:
    """The binary part files under {stage_path}/data (everything that is
    not the native npz container), in numeric-aware name order."""
    data_dir = os.path.join(stage_path, "data")
    return sorted(
        (
            f
            for f in glob.glob(os.path.join(data_dir, "*"))
            if os.path.isfile(f) and not f.endswith(".npz")
        ),
        key=_part_sort_key,
    )


def _iter_records(stage_path: str, read_one) -> Iterator:
    for file_path in _data_files(stage_path):
        with open(file_path, "rb") as f:
            stream = io.BufferedReader(f)
            while True:
                if not stream.peek(1):  # clean end of file
                    break
                try:
                    yield read_one(stream)
                except EOFError as e:  # mid-record cut = corruption, not EOF
                    raise IOError(
                        f"Corrupt reference model data file {file_path}: {e}"
                    ) from e


def load_reference_kmeans(stage_path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Decode a reference-written KMeans model directory; None if no
    binary part files exist."""
    records = list(_iter_records(stage_path, read_kmeans_model_data))
    if not records:
        return None
    # bounded KMeans writes one record; online writers append versions —
    # the LAST record is the current model (OnlineKMeansModel semantics)
    return records[-1]


def load_reference_logisticregression(stage_path: str) -> Optional[Tuple[np.ndarray, int]]:
    records = list(_iter_records(stage_path, read_logisticregression_model_data))
    if not records:
        return None
    return records[-1]


def load_reference_coefficient(stage_path: str) -> Optional[np.ndarray]:
    records = list(_iter_records(stage_path, read_dense_vector))
    if not records:
        return None
    return records[-1]


# ---------------------------------------------------------------------------
# Per-model codecs (one per reference ModelDataEncoder)
# ---------------------------------------------------------------------------
# Each encode_* mirrors the cited encoder; each load_reference_* decodes a
# reference-layout stage directory and returns a dict keyed like the
# model's native npz container so Model._load_extra handles both formats
# with the same code.


def encode_naivebayes_model_data(theta, pi, labels) -> bytes:
    """NaiveBayesModelData.ModelDataEncoder (NaiveBayesModelData.java:94-118):
    labels DenseVector + piArray DenseVector + int numLabels + int
    numFeatures + numLabels*numFeatures Map<Double, Double>."""
    out = [encode_dense_vector(labels), encode_dense_vector(pi)]
    num_labels = len(theta)
    num_features = len(theta[0]) if num_labels else 0
    out.append(_INT.pack(num_labels))
    out.append(_INT.pack(num_features))
    for label_maps in theta:
        for m in label_maps:
            out.append(encode_java_map(m, "double", "double"))
    return b"".join(out)


def read_naivebayes_model_data(stream) -> dict:
    labels = read_dense_vector(stream)
    pi = read_dense_vector(stream)
    (num_labels,) = _INT.unpack(_read_exact(stream, 4))
    (num_features,) = _INT.unpack(_read_exact(stream, 4))
    theta = np.empty((num_labels, num_features), dtype=object)
    for i in range(num_labels):
        for j in range(num_features):
            theta[i, j] = read_java_map(stream, "double", "double")
    return {"theta": theta, "piArray": pi, "labels": labels}


def encode_countvectorizer_model_data(vocabulary) -> bytes:
    """CountVectorizerModelData.ModelDataEncoder (:71-78): StringArray."""
    return encode_string_array(vocabulary)


def read_countvectorizer_model_data(stream) -> dict:
    return {"vocabulary": np.asarray(read_string_array(stream), dtype=object)}


def encode_idf_model_data(idf, doc_freq, num_docs: int) -> bytes:
    """IDFModelData.ModelDataEncoder (:79-89): idf DenseVector + long[]
    docFreq + long numDocs."""
    return (
        encode_dense_vector(idf)
        + encode_long_array(doc_freq)
        + _LONG.pack(int(num_docs))
    )


def read_idf_model_data(stream) -> dict:
    idf = read_dense_vector(stream)
    doc_freq = read_long_array(stream)
    (num_docs,) = _LONG.unpack(_read_exact(stream, 8))
    return {"idf": idf, "docFreq": doc_freq, "numDocs": np.int64(num_docs)}


def encode_imputer_model_data(surrogates: dict) -> bytes:
    """ImputerModelData.ModelDataEncoder (:75-81): Map<String, Double>."""
    return encode_java_map(surrogates, "string", "double")


def read_imputer_model_data(stream) -> dict:
    surrogates = read_java_map(stream, "string", "double")
    names = list(surrogates)
    return {
        "columnNames": np.asarray(names, dtype=object),
        "values": np.asarray([surrogates[k] for k in names], dtype=np.float64),
    }


def encode_kbinsdiscretizer_model_data(bin_edges) -> bytes:
    """KBinsDiscretizerModelData.ModelDataEncoder (:77-87): int numColumns +
    numColumns x double[]."""
    out = [_INT.pack(len(bin_edges))]
    for edges in bin_edges:
        out.append(encode_double_array(edges))
    return b"".join(out)


def read_kbinsdiscretizer_model_data(stream) -> dict:
    (num_cols,) = _INT.unpack(_read_exact(stream, 4))
    edges = np.empty(num_cols, dtype=object)
    for i in range(num_cols):
        edges[i] = read_double_array(stream)
    return {"binEdges": edges}


def encode_minhashlsh_model_data(
    num_hash_tables: int, num_hash_functions_per_table: int, coeff_a, coeff_b
) -> bytes:
    """MinHashLSHModelData.ModelDataEncoder (MinHashLSHModelData.java:173-182):
    int numHashTables + int numHashFunctionsPerTable + int[] randCoefficientA
    + int[] randCoefficientB."""
    return (
        _INT.pack(int(num_hash_tables))
        + _INT.pack(int(num_hash_functions_per_table))
        + encode_int_array(coeff_a)
        + encode_int_array(coeff_b)
    )


def read_minhashlsh_model_data(stream) -> dict:
    (tables,) = _INT.unpack(_read_exact(stream, 4))
    (per_table,) = _INT.unpack(_read_exact(stream, 4))
    a = read_int_array(stream)
    b = read_int_array(stream)
    return {
        "numHashTables": tables,
        "numHashFunctionsPerTable": per_table,
        "randCoefficientA": a.astype(np.int64),
        "randCoefficientB": b.astype(np.int64),
    }


def encode_maxabsscaler_model_data(max_vector) -> bytes:
    """MaxAbsScalerModelData.ModelDataEncoder (:74-78): one DenseVector."""
    return encode_dense_vector(max_vector)


def read_maxabsscaler_model_data(stream) -> dict:
    return {"maxVector": read_dense_vector(stream)}


def encode_minmaxscaler_model_data(min_vector, max_vector) -> bytes:
    """MinMaxScalerModelData.ModelDataEncoder (:80-85): min + max vectors."""
    return encode_dense_vector(min_vector) + encode_dense_vector(max_vector)


def read_minmaxscaler_model_data(stream) -> dict:
    return {
        "minVector": read_dense_vector(stream),
        "maxVector": read_dense_vector(stream),
    }


def encode_onehotencoder_model_record(column_index: int, max_index: int) -> bytes:
    """OneHotEncoderModelData.ModelDataEncoder (:71-76): Kryo Output
    writeInt x2 — LITTLE-endian, unlike every DataOutput format here. One
    record per column: (columnIndex, max category index)."""
    return struct.pack("<ii", int(column_index), int(max_index))


def read_onehotencoder_model_record(stream) -> Tuple[int, int]:
    return struct.unpack("<ii", _read_exact(stream, 8))


def encode_robustscaler_model_data(medians, ranges) -> bytes:
    """RobustScalerModelData.ModelDataEncoder (:79-85): medians + ranges."""
    return encode_dense_vector(medians) + encode_dense_vector(ranges)


def read_robustscaler_model_data(stream) -> dict:
    return {
        "medians": read_dense_vector(stream),
        "ranges": read_dense_vector(stream),
    }


def encode_standardscaler_model_data(mean, std) -> bytes:
    """StandardScalerModelData.ModelDataEncoder (:84-91): mean + std."""
    return encode_dense_vector(mean) + encode_dense_vector(std)


def read_standardscaler_model_data(stream) -> dict:
    return {"mean": read_dense_vector(stream), "std": read_dense_vector(stream)}


def encode_stringindexer_model_data(string_arrays) -> bytes:
    """StringIndexerModelData.ModelDataEncoder (:72-82): int numCols +
    numCols x StringArray."""
    out = [_INT.pack(len(string_arrays))]
    for arr in string_arrays:
        out.append(encode_string_array(arr))
    return b"".join(out)


def read_stringindexer_model_data(stream) -> dict:
    (num_cols,) = _INT.unpack(_read_exact(stream, 4))
    arrays = np.empty(num_cols, dtype=object)
    for i in range(num_cols):
        arrays[i] = np.asarray(read_string_array(stream), dtype=object)
    return {"stringArrays": arrays}


def encode_univariatefeatureselector_model_data(indices) -> bytes:
    """UnivariateFeatureSelectorModelData.ModelDataEncoder (:74-78): int[]."""
    return encode_int_array(indices)


def read_univariatefeatureselector_model_data(stream) -> dict:
    return {"indices": read_int_array(stream).astype(np.int64)}


def encode_variancethresholdselector_model_data(num_features: int, indices) -> bytes:
    """VarianceThresholdSelectorModelData.ModelDataEncoder (:79-84): int
    numOfFeatures + int[] indices."""
    return _INT.pack(int(num_features)) + encode_int_array(indices)


def read_variancethresholdselector_model_data(stream) -> dict:
    (num_features,) = _INT.unpack(_read_exact(stream, 4))
    return {
        "numOfFeatures": num_features,
        "indices": read_int_array(stream).astype(np.int64),
    }


def encode_vectorindexer_model_data(category_maps: dict) -> bytes:
    """VectorIndexerModelData.ModelDataEncoder (:81-92):
    Map<Integer, Map<Double, Integer>> categoryMaps."""
    inner = (
        lambda m: encode_java_map(m, "double", "int"),
        lambda s: read_java_map(s, "double", "int"),
    )
    return encode_java_map(category_maps, "int", inner)


def read_vectorindexer_model_data(stream) -> dict:
    inner = (
        lambda m: encode_java_map(m, "double", "int"),
        lambda s: read_java_map(s, "double", "int"),
    )
    category_maps = read_java_map(stream, "int", inner)
    cols = sorted(category_maps)
    keys = np.empty(len(cols), dtype=object)
    for i, c in enumerate(cols):
        m = category_maps[c]
        keys[i] = np.asarray(sorted(m, key=m.get), dtype=np.float64)
    return {"columns": np.asarray(cols, dtype=np.int64), "keys": keys}


def encode_knn_model_data(features, labels) -> bytes:
    """KnnModelData.ModelDataEncoder (KnnModelData.java:89-94): packed
    (featureDim, numPoints) DenseMatrix + featureNormSquares DenseVector +
    labels DenseVector. ``features`` is this framework's (numPoints,
    featureDim) row layout."""
    F = np.asarray(features, dtype=np.float64)
    norms = np.sum(F * F, axis=1)
    return (
        encode_dense_matrix(F.T)
        + encode_dense_vector(norms)
        + encode_dense_vector(labels)
    )


def read_knn_model_data(stream) -> Tuple[np.ndarray, np.ndarray]:
    matrix = read_dense_matrix(stream)
    read_dense_vector(stream)  # featureNormSquares: recomputed on load
    labels = read_dense_vector(stream)
    return matrix.T, labels


def write_reference_data_file(stage_path: str, payload: bytes, part: int = 0) -> str:
    """Write a reference-layout binary part file (fixture/export helper)."""
    data_dir = os.path.join(stage_path, "data")
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, f"part-0-{part}")
    with open(path, "wb") as f:
        f.write(payload)
    return path


def _last_record_loader(read_one):
    """Directory loader for single-record model data (the bounded
    estimators write one record; online writers append versions — the LAST
    record is the current model)."""

    def load(stage_path: str):
        records = list(_iter_records(stage_path, read_one))
        return records[-1] if records else None

    return load


load_reference_naivebayes = _last_record_loader(read_naivebayes_model_data)
load_reference_countvectorizer = _last_record_loader(read_countvectorizer_model_data)
load_reference_idf = _last_record_loader(read_idf_model_data)
load_reference_imputer = _last_record_loader(read_imputer_model_data)
load_reference_kbinsdiscretizer = _last_record_loader(read_kbinsdiscretizer_model_data)
load_reference_minhashlsh = _last_record_loader(read_minhashlsh_model_data)
load_reference_maxabsscaler = _last_record_loader(read_maxabsscaler_model_data)
load_reference_minmaxscaler = _last_record_loader(read_minmaxscaler_model_data)
load_reference_robustscaler = _last_record_loader(read_robustscaler_model_data)
load_reference_standardscaler = _last_record_loader(read_standardscaler_model_data)
load_reference_stringindexer = _last_record_loader(read_stringindexer_model_data)
load_reference_univariatefeatureselector = _last_record_loader(
    read_univariatefeatureselector_model_data
)
load_reference_variancethresholdselector = _last_record_loader(
    read_variancethresholdselector_model_data
)
load_reference_vectorindexer = _last_record_loader(read_vectorindexer_model_data)


def load_reference_onehotencoder(stage_path: str) -> Optional[dict]:
    """OneHot model data is a STREAM of (columnIndex, maxIndex) Tuple2
    records, one per column, possibly split across part files
    (OneHotEncoder.java:236). categorySizes[i] = maxIndex + 1, this
    framework's per-column 'max index + 1' convention
    (OneHotEncoderModel.java:168 adds the dropLast offset at transform
    time, as does OneHotEncoderModel.transform here)."""
    records = list(_iter_records(stage_path, read_onehotencoder_model_record))
    if not records:
        return None
    sizes = {col: max_idx + 1 for col, max_idx in records}
    return {
        "categorySizes": np.asarray(
            [sizes[i] for i in range(len(sizes))], dtype=np.int64
        )
    }


def load_reference_knn(stage_path: str) -> Optional[dict]:
    """Knn writes one packed-matrix record per task bundle
    (Knn.java:116); all bundles together are the model — concatenate."""
    records = list(_iter_records(stage_path, read_knn_model_data))
    if not records:
        return None
    return {
        "features": np.concatenate([r[0] for r in records], axis=0),
        "labels": np.concatenate([r[1] for r in records]),
    }
