"""Binary codecs for model data written by the reference's Java encoders.

The reference persists model data as binary part files under
`{stage_path}/data/`, one encoder per model class
(ReadWriteUtils.saveModelData/loadModelData,
flink-ml-core/.../util/ReadWriteUtils.java:440-460). The wire format is
Java DataOutput (big-endian):

- DenseVector  (linalg/typeinfo/DenseVectorSerializer.java:78-99):
  int32 length + length x float64 values.
- KMeansModelData  (clustering/kmeans/KMeansModelData.java:140-154):
  int32 numCentroids + numCentroids x DenseVector + weights DenseVector.
- LogisticRegressionModelData
  (classification/logisticregression/LogisticRegressionModelData.java:
  110-121): DenseVector coefficient + int64 modelVersion.
- LinearSVCModelData / LinearRegressionModelData mirror the LR layout
  minus the version long (a single DenseVector coefficient).

These codecs let models LOAD reference-written directories (the npz
native format stays the default for save) and write reference-format
fixtures for tests. Encoders/decoders are exact inverses; the committed
fixture under tests/fixtures/ was produced by the encoders here,
implementing the cited Java formats byte for byte.
"""

from __future__ import annotations

import glob
import io
import os
import struct
from typing import Iterator, List, Optional, Tuple

import numpy as np

_INT = struct.Struct(">i")
_LONG = struct.Struct(">q")


def encode_dense_vector(values: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(np.asarray(values, dtype=np.float64))
    return _INT.pack(arr.shape[0]) + arr.astype(">f8").tobytes()


def read_dense_vector(stream: io.BufferedIOBase) -> np.ndarray:
    raw = stream.read(4)
    if len(raw) < 4:
        raise EOFError("end of stream")
    (length,) = _INT.unpack(raw)
    data = stream.read(8 * length)
    if len(data) < 8 * length:
        raise EOFError("truncated DenseVector payload")
    return np.frombuffer(data, dtype=">f8").astype(np.float64)


def encode_kmeans_model_data(centroids: np.ndarray, weights: np.ndarray) -> bytes:
    out = [_INT.pack(int(np.shape(centroids)[0]))]
    for c in np.asarray(centroids, dtype=np.float64):
        out.append(encode_dense_vector(c))
    out.append(encode_dense_vector(weights))
    return b"".join(out)


def read_kmeans_model_data(stream) -> Tuple[np.ndarray, np.ndarray]:
    raw = stream.read(4)
    if len(raw) < 4:
        raise EOFError("end of stream")
    (num,) = _INT.unpack(raw)
    centroids = np.stack([read_dense_vector(stream) for _ in range(num)])
    weights = read_dense_vector(stream)
    return centroids, weights


def encode_logisticregression_model_data(
    coefficient: np.ndarray, model_version: int = 0
) -> bytes:
    return encode_dense_vector(coefficient) + _LONG.pack(int(model_version))


def read_logisticregression_model_data(stream) -> Tuple[np.ndarray, int]:
    coefficient = read_dense_vector(stream)
    raw = stream.read(8)
    if len(raw) < 8:
        raise EOFError("truncated modelVersion")
    (version,) = _LONG.unpack(raw)
    return coefficient, version


def encode_coefficient_model_data(coefficient: np.ndarray) -> bytes:
    """LinearSVCModelData / LinearRegressionModelData: one DenseVector."""
    return encode_dense_vector(coefficient)


def _part_sort_key(path: str):
    """Numeric-aware part-file ordering: 'part-0-10' sorts after 'part-0-9'
    (plain lexical order would make records[-1] a stale model once a
    writer produces 10+ parts)."""
    name = os.path.basename(path)
    pieces = name.replace("_", "-").split("-")
    return [int(p) if p.isdigit() else p for p in pieces]


def _data_files(stage_path: str) -> List[str]:
    """The binary part files under {stage_path}/data (everything that is
    not the native npz container), in numeric-aware name order."""
    data_dir = os.path.join(stage_path, "data")
    return sorted(
        (
            f
            for f in glob.glob(os.path.join(data_dir, "*"))
            if os.path.isfile(f) and not f.endswith(".npz")
        ),
        key=_part_sort_key,
    )


def _iter_records(stage_path: str, read_one) -> Iterator:
    for file_path in _data_files(stage_path):
        with open(file_path, "rb") as f:
            stream = io.BufferedReader(f)
            while True:
                if not stream.peek(1):  # clean end of file
                    break
                try:
                    yield read_one(stream)
                except EOFError as e:  # mid-record cut = corruption, not EOF
                    raise IOError(
                        f"Corrupt reference model data file {file_path}: {e}"
                    ) from e


def load_reference_kmeans(stage_path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Decode a reference-written KMeans model directory; None if no
    binary part files exist."""
    records = list(_iter_records(stage_path, read_kmeans_model_data))
    if not records:
        return None
    # bounded KMeans writes one record; online writers append versions —
    # the LAST record is the current model (OnlineKMeansModel semantics)
    return records[-1]


def load_reference_logisticregression(stage_path: str) -> Optional[Tuple[np.ndarray, int]]:
    records = list(_iter_records(stage_path, read_logisticregression_model_data))
    if not records:
        return None
    return records[-1]


def load_reference_coefficient(stage_path: str) -> Optional[np.ndarray]:
    records = list(_iter_records(stage_path, read_dense_vector))
    if not records:
        return None
    return records[-1]


def write_reference_data_file(stage_path: str, payload: bytes, part: int = 0) -> str:
    """Write a reference-layout binary part file (fixture/export helper)."""
    data_dir = os.path.join(stage_path, "data")
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, f"part-0-{part}")
    with open(path, "wb") as f:
        f.write(payload)
    return path
