"""One-transfer device→host readback of multiple arrays.

On the remote-attached TPU every array's first readback costs a full
~100ms host round trip regardless of size, so a fit that pulls
(centroids, counts) or (mean, std) separately pays the tunnel twice.
`packed_device_get` flattens and concatenates the arrays device-side and
performs ONE explicit `jax.device_get`, then splits on host.

The transfer is explicit on purpose: tests pin the one-readback-per-fit
contract by running fits under `jax.transfer_guard("disallow")`, which
blocks implicit transfers (stray `np.asarray` on a device array) while
letting this helper's `device_get` through.

Caveat: values are packed in the promoted common dtype (float32 when x64
is off). Integer inputs above 2**24 would lose precision — callers on
those paths keep their own packing (see ops/optimizer._pack_result).
"""

from __future__ import annotations

from typing import List

import numpy as np


def packed_device_get(*arrays, sync_kind: str = "readback") -> List[np.ndarray]:
    """Return host copies of ``arrays`` via at most one D2H transfer.

    Host inputs pass through as-is (never uploaded just to be pulled
    back); device inputs are flattened into one concatenated transfer and
    restored to their original shapes AND dtypes on the host. A call with
    any device input is one blocking host↔device synchronization point and
    is accounted as ``iteration.host_sync.<sync_kind>`` — callers on named
    paths (fit results, checkpoint snapshots) pass their kind."""
    import jax
    import jax.numpy as jnp

    import time

    from ..obs import tracing

    device_idx = [i for i, a in enumerate(arrays) if isinstance(a, jax.Array)]
    out: List = [None] * len(arrays)
    for i, a in enumerate(arrays):
        if i not in device_idx:
            out[i] = np.asarray(a)
    if not device_idx:
        return out
    tracing.account_host_sync(sync_kind)
    if len(device_idx) == 1:
        i = device_idx[0]
        t0 = time.perf_counter()
        out[i] = np.asarray(jax.device_get(arrays[i]))
        tracing.account_readback(out[i].nbytes, time.perf_counter() - t0)
        return out
    devs = [arrays[i] for i in device_idx]
    shapes = [a.shape for a in devs]
    dtypes = [a.dtype for a in devs]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    dt = dtypes[0]
    for d in dtypes[1:]:
        dt = jnp.promote_types(dt, d)
    packed = jnp.concatenate([jnp.ravel(a).astype(dt) for a in devs])
    t0 = time.perf_counter()
    host = np.asarray(jax.device_get(packed))
    tracing.account_readback(
        host.nbytes, time.perf_counter() - t0, arrays=len(device_idx)
    )
    off = 0
    for i, shape, size, dtype in zip(device_idx, shapes, sizes, dtypes):
        out[i] = host[off : off + size].reshape(shape).astype(dtype)
        off += size
    return out
