"""Bounded-stream utility ops over Tables / StreamTables.

TPU-native analogues of the reference's DataStreamUtils batch helpers
(`common/datastream/DataStreamUtils.java`): `aggregate` (:182) — a generic
accumulator fold over a bounded stream with a final merge, and `sample`
(:212) — uniform reservoir sampling of k rows. The reference implements
these as custom BoundedOneInput operators with ListState; here a
StreamTable is already an iterator of bounded mini-batch Tables, so the
same contracts become host-side folds over batches with vectorized
per-batch work (the accumulator math stays numpy/jax-friendly).
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional, TypeVar, Union

import numpy as np

from ..table import StreamTable, Table

A = TypeVar("A")
R = TypeVar("R")

__all__ = [
    "aggregate",
    "event_time_window_groups",
    "event_time_groups_from_table",
    "map_partition",
    "reduce",
    "sample",
    "window_all_and_process",
    "iter_batches",
]


def iter_batches(data: Union[Table, StreamTable]) -> Iterable[Table]:
    """Uniform batch view: a bounded Table is a one-batch stream."""
    if isinstance(data, Table):
        return [data]
    return data


def _concat_all(tables: List[Table]) -> Table:
    """Concatenate batches linearly: one np.concatenate per plain ndarray
    column; anything fancier (sparse, token, mixed dtypes) folds through
    Table.concat. The pairwise fold alone is O(B^2) row copying."""
    if len(tables) == 1:
        return tables[0]
    cols = {}
    for name in tables[0].column_names:
        parts = [t.column(name) for t in tables]
        if not all(
            isinstance(x, np.ndarray) and x.dtype == parts[0].dtype for x in parts
        ):
            break
        cols[name] = np.concatenate(parts)
    else:
        if all(t.column_names == tables[0].column_names for t in tables):
            return Table(cols)
    out = tables[0]
    for b in tables[1:]:
        out = out.concat(b)
    return out


def event_time_groups_from_table(table: Table, windows, timestamp_col: str = "timestamp"):
    """Validate the timestamp column and return event-time row groups —
    the single entry point both window_all_and_process and windowed stages
    (AgglomerativeClustering) share."""
    if timestamp_col not in table.column_names:
        raise ValueError(
            f"Event-time windows need a {timestamp_col!r} column carrying "
            "each record's event time in milliseconds"
        )
    return event_time_window_groups(np.asarray(table.column(timestamp_col)), windows)


def aggregate(
    data: Union[Table, StreamTable],
    create_accumulator: Callable[[], A],
    add: Callable[[A, Table], A],
    get_result: Callable[[A], R],
    merge: Optional[Callable[[A, A], A]] = None,
) -> R:
    """Generic bounded aggregation (DataStreamUtils.aggregate, :182): fold
    every batch into an accumulator, then extract the result. `add` receives
    a whole mini-batch Table (vectorize inside it); `merge` is accepted for
    API parity with partition-parallel callers that combine per-shard
    accumulators themselves."""
    acc = create_accumulator()
    for batch in iter_batches(data):
        acc = add(acc, batch)
    return get_result(acc)


def sample(
    data: Union[Table, StreamTable], num_samples: int, seed: int = 0
) -> Table:
    """Uniform reservoir sample of `num_samples` rows without replacement
    (DataStreamUtils.sample, :212 — Algorithm R, batch-vectorized: each
    incoming batch draws its candidate positions in one RNG call instead of
    a per-row coin flip)."""
    if num_samples <= 0:
        raise ValueError("num_samples must be > 0")
    rng = np.random.RandomState(seed)
    reservoir: Optional[Table] = None
    seen = 0
    for batch in iter_batches(data):
        n = batch.num_rows
        if n == 0:
            continue
        if reservoir is None or reservoir.num_rows < num_samples:
            have = 0 if reservoir is None else reservoir.num_rows
            take = min(num_samples - have, n)
            head = batch.take(np.arange(take))
            reservoir = head if reservoir is None else reservoir.concat(head)
            seen += take
            if take == n:
                continue
            batch = batch.take(np.arange(take, n))
            n = batch.num_rows
        # each remaining row i (global index seen+i) replaces a reservoir
        # slot with probability k/(seen+i+1), landing in a uniform slot
        global_idx = seen + np.arange(n) + 1
        accept = rng.random(n) < num_samples / global_idx
        slots = rng.randint(0, num_samples, size=n)
        seen += n
        if not np.any(accept):
            continue
        # later rows overwrite earlier ones in the same slot (stream order)
        replace_rows = np.nonzero(accept)[0]
        keep = np.arange(reservoir.num_rows)
        incoming: List[int] = [-1] * num_samples
        for i in replace_rows:
            incoming[slots[i]] = int(i)
        repl_slots = [s for s, i in enumerate(incoming) if i >= 0]
        repl_idx = [incoming[s] for s in repl_slots]
        survivors = np.setdiff1d(keep, np.asarray(repl_slots, dtype=np.int64))
        new_rows = batch.take(np.asarray(repl_idx, dtype=np.int64))
        reservoir_kept = reservoir.take(survivors)
        reservoir = reservoir_kept.concat(new_rows)
    if reservoir is None:
        raise ValueError("cannot sample from an empty stream")
    return reservoir


def map_partition(
    data: Union[Table, StreamTable], fn: Callable[[Table], Table]
) -> Union[Table, StreamTable]:
    """Apply a whole-partition function to each bounded batch
    (DataStreamUtils.mapPartition, :115). The reference hands the operator
    an iterator over its partition's rows; the columnar analogue hands `fn`
    a whole mini-batch Table and keeps the stream shape: a bounded Table
    maps to a Table, a StreamTable maps lazily batch-by-batch."""
    if isinstance(data, Table):
        return fn(data)
    return StreamTable(fn(batch) for batch in data)


def reduce(
    data: Union[Table, StreamTable], fn: Callable[[Table, Table], Table]
) -> Table:
    """Pairwise-fold every batch into one Table
    (DataStreamUtils.reduce, :132)."""
    acc = None
    for batch in iter_batches(data):
        acc = batch if acc is None else fn(acc, batch)
    if acc is None:
        raise ValueError("reduce over an empty stream")
    return acc


def event_time_window_groups(
    timestamps: np.ndarray, windows
) -> List[np.ndarray]:
    """Row-index groups for event-time window descriptors over a bounded
    input, in firing (window-start / session-start) order.

    Tumbling (TumblingEventTimeWindows.assignWindows): a record at time t
    belongs to the window starting at ``t - (t % size)`` (epoch-aligned).
    Session (EventTimeSessionWindows): windows merge while consecutive
    event times are within ``gap`` of each other."""
    from ..common.window import EventTimeSessionWindows, EventTimeTumblingWindows

    ts = np.asarray(timestamps, dtype=np.int64)
    if isinstance(windows, EventTimeTumblingWindows):
        size = int(windows.size_ms)
        if size <= 0:
            raise ValueError("Event-time tumbling window size must be positive")
        # numpy % is floorMod, so this floor-aligns negatives correctly too
        starts = ts - (ts % size)
        order = np.argsort(starts, kind="stable")
        uniq, first = np.unique(starts[order], return_index=True)
        bounds = list(first) + [len(order)]
        return [order[bounds[i] : bounds[i + 1]] for i in range(len(uniq))]
    if isinstance(windows, EventTimeSessionWindows):
        gap = int(windows.gap_ms)
        if gap <= 0:
            raise ValueError("Session gap must be positive")
        order = np.argsort(ts, kind="stable")
        if order.size == 0:
            return []
        sorted_ts = ts[order]
        breaks = np.nonzero(np.diff(sorted_ts) > gap)[0] + 1
        return [np.sort(g) for g in np.split(order, breaks)]
    raise TypeError(f"Not an event-time descriptor: {type(windows).__name__}")


def window_all_and_process(
    data: Union[Table, StreamTable],
    windows,
    fn: Callable[[Table], Table],
    timestamp_col: str = "timestamp",
    clock: Optional[Callable[[], float]] = None,
) -> Union[Table, StreamTable]:
    """Re-chunk the input by a window descriptor and apply `fn` per window
    (DataStreamUtils.windowAllAndProcess, :262 — the mechanism behind
    windowed local processing like AgglomerativeClustering's per-window
    clustering).

    GlobalWindows = one window over the whole bounded input (the
    endOfStreamWindows behaviour — a StreamTable is materialized, so pass
    bounded streams only); CountTumblingWindows(k) = windows of exactly k rows —
    Flink count windows only fire when FULL, so the ragged tail is
    dropped.

    Event-time windows read each record's event time (ms) from
    ``timestamp_col`` — the bounded analogue of Flink's stream timestamps;
    windows fire in window-start order once the bounded input ends
    (watermark -> +inf). Processing-time windows stamp each incoming BATCH
    with the wall clock (``clock``, default time.monotonic, in seconds;
    injectable for deterministic tests) and fire a window when a batch
    arrives past its boundary — a bounded Table arrives all at once and is
    one window, matching what a fast bounded source degenerates to in the
    reference."""
    import time as _time

    from ..common.window import (
        CountTumblingWindows,
        EventTimeSessionWindows,
        EventTimeTumblingWindows,
        GlobalWindows,
        ProcessingTimeSessionWindows,
        ProcessingTimeTumblingWindows,
    )

    if isinstance(windows, (EventTimeTumblingWindows, EventTimeSessionWindows)):
        batches = list(iter_batches(data))
        if not batches:
            return StreamTable([]) if isinstance(data, StreamTable) else Table({})
        whole = _concat_all(batches)
        groups = event_time_groups_from_table(whole, windows, timestamp_col)
        results = [fn(whole.take(g)) for g in groups]
        if isinstance(data, StreamTable):
            return StreamTable(results)
        if not results:
            return Table({})
        return _concat_all(results)

    if isinstance(
        windows, (ProcessingTimeTumblingWindows, ProcessingTimeSessionWindows)
    ):
        # validate before the bounded-Table fast path: an invalid descriptor
        # must fail regardless of input type
        if isinstance(windows, ProcessingTimeTumblingWindows):
            size_s = int(windows.size_ms) / 1000.0
            if size_s <= 0:
                raise ValueError("Processing-time window size must be positive")
        else:
            gap_s = int(windows.gap_ms) / 1000.0
            if gap_s <= 0:
                raise ValueError("Session gap must be positive")
        if isinstance(data, Table):
            # a bounded table "arrives" at one instant: one window
            return fn(data)
        clock = clock or _time.monotonic
        if isinstance(windows, ProcessingTimeTumblingWindows):

            def proc_chunks() -> Iterable[Table]:
                pending: List[Table] = []
                window_end: Optional[float] = None
                for batch in data:
                    now = clock()
                    if window_end is None:
                        window_end = (now // size_s + 1) * size_s
                    elif now >= window_end:
                        if pending:
                            yield _concat_all(pending)
                        pending = []
                        window_end = (now // size_s + 1) * size_s
                    pending.append(batch)
                if pending:
                    yield _concat_all(pending)

            return StreamTable(fn(w) for w in proc_chunks())

        def session_chunks() -> Iterable[Table]:
            pending: List[Table] = []
            last: Optional[float] = None
            for batch in data:
                now = clock()
                if last is not None and now - last > gap_s and pending:
                    yield _concat_all(pending)
                    pending = []
                pending.append(batch)
                last = now
            if pending:
                yield _concat_all(pending)

        return StreamTable(fn(w) for w in session_chunks())

    if isinstance(windows, GlobalWindows):
        # ONE window over the whole BOUNDED input (endOfStreamWindows):
        # a stream materializes first so Table and StreamTable layouts of
        # the same data give identical results. This helper is for bounded
        # inputs only — unbounded per-batch processing lives in the online
        # iteration runtime, not here.
        batches = list(iter_batches(data))
        if not batches:
            return StreamTable([]) if isinstance(data, StreamTable) else Table({})
        whole = _concat_all(batches)
        result = fn(whole)
        return StreamTable([result]) if isinstance(data, StreamTable) else result
    if isinstance(windows, CountTumblingWindows):
        size = int(windows.size)

        def chunks() -> Iterable[Table]:
            # accumulate whole batches and concat once per emitted window —
            # re-concatenating the pending buffer per batch would be
            # quadratic when batches are much smaller than the window
            pending: List[Table] = []
            pending_rows = 0
            for batch in iter_batches(data):
                pending.append(batch)
                pending_rows += batch.num_rows
                while pending_rows >= size:
                    merged = _concat_all(pending)
                    off = 0
                    while merged.num_rows - off >= size:
                        yield merged.take(np.arange(off, off + size))
                        off += size
                    pending = (
                        [merged.take(np.arange(off, merged.num_rows))]
                        if off < merged.num_rows
                        else []
                    )
                    pending_rows = merged.num_rows - off
            # ragged tail dropped: count windows fire only when full

        if isinstance(data, Table):
            results = [fn(w) for w in chunks()]
            if not results:
                # no full window ever fires — the reference emits an empty
                # (typed) stream; without static typing the closest analogue
                # is a column-less empty Table
                return Table({})
            out = results[0]
            for r in results[1:]:
                out = out.concat(r)
            return out
        return StreamTable(fn(w) for w in chunks())
    raise NotImplementedError(
        f"{type(windows).__name__} needs event-/processing-time semantics; "
        "use the online iteration runtime for time windows"
    )
