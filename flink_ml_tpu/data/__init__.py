"""Data-plane infrastructure: device-resident epoch caching for bounded
iterations over cached streams (`devicecache`). The host-side spillable
segment store lives in `flink_ml_tpu.native.datacache`; this package holds
the HBM tier stacked on top of it."""

from .devicecache import CachedEpochLoader, DeviceEpochCache  # noqa: F401
