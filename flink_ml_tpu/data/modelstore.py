"""Multi-tenant device-resident model store — HBM paging at model-count scale.

`DeviceEpochCache` (devicecache.py) answers "which *epochs* stay resident";
this module answers the serving-tier question ROADMAP item 3 poses: which
*models* stay resident when one mesh serves far more tenants than fit in
HBM. A `ModelStore` holds registered `(key -> model)` entries and pages
each model's kernel constants host<->HBM under an LRU byte budget
(`config.model_store_bytes`):

- **Page-in rides the sanctioned funnel.** The store never stages bytes
  itself: `page_in` calls each served stage's `device_constants()`, which
  uploads through `prefetch.stage_to_device(..., category="model")` — so
  every resident model byte is h2d-accounted and ledgered under the
  memledger `model` category, and `hbm.live.model` IS the store's
  residency. (tpulint's `unledgered-residency` rule sanctions `page_in`
  alongside the other funnels for exactly this reason.)
- **Page-out is deterministic.** `invalidate_device_constants()` drops the
  only persistent reference to the staged tree; the tracked entries'
  `weakref.finalize` release on the spot (CPython refcounting), so the
  ledger falls the moment the store decides, not at some later GC.
- **Zero recompiles by construction.** Model constants are *runtime
  operands* on the fused path: `FusedSegment.execute` re-reads
  `device_constants()` per dispatch and the plan-cache token excludes
  swap-capable array identities, so a page-out/page-in cycle re-uploads
  the same avals into the same compiled program. The `servingSlo` bench
  pins `jit.compiles` at 0 across steady-state paging.
- **Admission is conservative.** Eviction is driven by the *host-side*
  byte estimate of each model's kernel constants, which (under jax's
  default x64-disabled canonicalization) is >= the device-resident bytes
  — so `hbm.live.model` can never exceed `budget_bytes` through this
  store, even before the post-staging measurement lands.

Integration points: an optional per-key `lifecycle.ModelLifecycle`
(version ring, promotion gate, auto-rollback — promote through
`ModelStore.promote` so residency accounting follows the republish), and
an optional per-key admission `quota` consumed by
`serving.MicroBatchServer`'s per-tenant reject-policy gates.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

from .. import config, flow
from ..api import AlgoOperator
from ..obs import memledger
from ..pipeline import PipelineModel
from ..utils import metrics

__all__ = ["ModelStore", "ModelStoreBudgetExceeded"]

_UNSET = object()


class ModelStoreBudgetExceeded(RuntimeError):
    """A single model's estimated constants exceed the whole store budget
    — no eviction schedule can make it fit. Carries the numbers."""

    def __init__(self, key: str, nbytes: int, budget: int):
        super().__init__(
            f"model {key!r} needs ~{nbytes} constant bytes but "
            f"config.model_store_bytes={budget}"
        )
        self.key, self.nbytes, self.budget = key, nbytes, budget


def _served_stages(model) -> List[Any]:
    """The stages whose `device_constants()` are this model's resident
    footprint: the AlgoOperator members of a PipelineModel, or the model
    itself."""
    if isinstance(model, PipelineModel):
        return [s for s in model.stages if isinstance(s, AlgoOperator)]
    if isinstance(model, AlgoOperator):
        return [model]
    raise TypeError(
        f"ModelStore pages PipelineModel/AlgoOperator stages, got {type(model).__name__}"
    )


def _host_nbytes(tree) -> int:
    """Host-side bytes of a kernel-constants tree — the conservative
    admission estimate (>= device bytes under default canonicalization:
    f64/i64 hosts stage as f32/i32)."""
    import jax

    from ..table import register_device_pytrees

    register_device_pytrees()
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 8))
    return total


@dataclass
class _StoredModel:
    model: Any
    stages: List[Any]
    lifecycle: Any = None
    quota: Optional[int] = None
    est_nbytes: int = 0  # host-side estimate (admission)
    dev_nbytes: int = 0  # ledgered device bytes while resident
    resident: bool = False
    page_ins: int = 0


class ModelStore:
    """LRU-paged registry of served models, ledgered under `model`.

    `budget_bytes` defaults to `config.model_store_bytes` (None =
    unbounded). `acquire(key)` returns the model ready to dispatch,
    paging it in (and evicting least-recently-used residents first) as
    needed; `prefetch(keys)` warms upcoming tenants off the dispatch
    path. All mutation is lock-serialized — the dispatch worker and a
    prefetch worker may share one store.

    The store owns paging from `register` on: registration invalidates
    any externally staged constants so residency starts clean, and
    callers must route republishes through `promote` (or call
    `refresh(key)`) so accounting follows the new arrays.
    """

    def __init__(self, budget_bytes=_UNSET, name: str = "modelstore"):
        self.name = name
        self._budget = config.model_store_bytes if budget_bytes is _UNSET else budget_bytes
        if self._budget is not None:
            self._budget = max(0, int(self._budget))
        self._entries: "OrderedDict[str, _StoredModel]" = OrderedDict()
        self._lock = threading.RLock()
        self._used = 0  # ledgered device bytes of resident entries
        # learned device/host-estimate inflation (>= 1.0): real devices
        # pad constants past the host estimate (lane-aligned layouts), so
        # reserving by raw estimates would let residency overshoot the
        # budget. Every staging updates the max observed ratio and later
        # reservations are inflated by it; on backends where device bytes
        # <= estimate (CPU canonicalization) this stays exactly 1.0
        self._infl = 1.0
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    # -- registry ------------------------------------------------------------
    def register(
        self,
        key: str,
        model,
        lifecycle=None,
        quota: Optional[int] = None,
    ) -> None:
        """Add (or replace) a served model. `lifecycle` attaches a
        per-model version ring; `quota` is the tenant's admission-queue
        share (consumed by MicroBatchServer's per-tenant reject gates)."""
        stages = _served_stages(model)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None and old.resident:
                self._page_out_locked(key, old)
            entry = _StoredModel(
                model=model,
                stages=stages,
                lifecycle=lifecycle,
                quota=None if quota is None else max(1, int(quota)),
            )
            for stage in stages:  # start clean: the store owns residency now
                stage.invalidate_device_constants()
            entry.est_nbytes = sum(_host_nbytes(s._kernel_constants()) for s in stages)
            if self._budget is not None and entry.est_nbytes > self._budget:
                raise ModelStoreBudgetExceeded(key, entry.est_nbytes, self._budget)
            self._entries[key] = entry
            metrics.set_gauge("modelstore.models", len(self._entries))

    def unregister(self, key: str) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None and entry.resident:
                self._page_out_locked(key, entry)
            metrics.set_gauge("modelstore.models", len(self._entries))

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def lifecycle(self, key: str):
        return self._entry(key).lifecycle

    def quota(self, key: str) -> Optional[int]:
        return self._entry(key).quota

    def estimated_nbytes(self, key: str) -> int:
        """The host-side admission estimate for one model — what sizing a
        budget against N models costs (bench/example use this to pick a
        `model_store_bytes` that forces paging)."""
        return self._entry(key).est_nbytes

    def _entry(self, key: str) -> _StoredModel:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                raise KeyError(f"model {key!r} is not registered in {self.name}")
            return entry

    # -- paging --------------------------------------------------------------
    def acquire(self, key: str):
        """The dispatch-path read: page `key` in if needed, mark it
        most-recently-used, return its model."""
        return self.page_in(key).model

    def page_in(self, key: str) -> _StoredModel:
        """Make `key` device-resident (the sanctioned paging funnel: all
        bytes stage through each stage's `device_constants()` ->
        `prefetch.stage_to_device(category="model")`). Evicts LRU
        residents first so estimated residency never exceeds the budget."""
        with self._lock:
            entry = self._entry(key)
            self._entries.move_to_end(key)
            if entry.resident and all(
                "_device_consts" in s.__dict__ for s in entry.stages
            ):
                self._hits += 1
                metrics.inc_counter("modelstore.hit")
                return entry
            self._misses += 1
            metrics.inc_counter("modelstore.miss")
            if entry.resident:
                # externally invalidated (e.g. a republish outside
                # `promote`) — drop stale accounting and restage
                self._page_out_locked(key, entry, count_eviction=False)
            self._ensure_room(key, math.ceil(entry.est_nbytes * self._infl))
            dev = 0
            for stage in entry.stages:
                dev += memledger.tracked_nbytes(stage.device_constants())
            if entry.est_nbytes > 0:
                self._infl = max(self._infl, dev / entry.est_nbytes)
            entry.resident = True
            entry.dev_nbytes = dev
            entry.page_ins += 1
            self._used += dev
            metrics.inc_counter("modelstore.pageIn")
            metrics.inc_counter("modelstore.pageInBytes", dev)
            metrics.set_gauge("modelstore.bytes", self._used)
            return entry

    def page_out(self, key: str) -> None:
        """Release `key`'s device constants (the ledger entries close via
        the dropped references — deterministic on CPython)."""
        with self._lock:
            entry = self._entry(key)
            if entry.resident:
                self._page_out_locked(key, entry)

    def _page_out_locked(self, key: str, entry: _StoredModel, count_eviction: bool = True) -> None:
        for stage in entry.stages:
            stage.invalidate_device_constants()
        self._used -= entry.dev_nbytes
        if count_eviction:
            self._evictions += 1
            metrics.inc_counter("modelstore.evict")
            metrics.inc_counter("modelstore.evictBytes", entry.dev_nbytes)
        entry.resident = False
        entry.dev_nbytes = 0
        metrics.set_gauge("modelstore.bytes", self._used)

    def _ensure_room(self, incoming_key: str, est_nbytes: int) -> None:
        """Evict least-recently-used residents until the conservative
        estimate fits. `_used` tracks *ledgered* bytes (<= estimates), so
        `hbm.live.model` stays <= budget through the staging itself."""
        if self._budget is None:
            return
        if est_nbytes > self._budget:
            raise ModelStoreBudgetExceeded(incoming_key, est_nbytes, self._budget)
        while self._used + est_nbytes > self._budget:
            victim = next(
                (k for k, e in self._entries.items() if e.resident and k != incoming_key),
                None,
            )
            if victim is None:  # accounting can't shrink further
                break
            self._page_out_locked(victim, self._entries[victim])

    def prefetch(self, keys: Iterable[str], wait: bool = True):
        """Warm `keys` ahead of their dispatches — the miss-staging path
        the dispatch loop never pays. `wait=False` pages on a background
        `flow.spawn` worker (store-lock serialized against the dispatch
        path) and returns the worker handle."""
        keys = [k for k in keys]

        def _warm():
            for k in keys:
                metrics.inc_counter("modelstore.prefetch")
                self.page_in(k)

        if wait:
            _warm()
            return None
        return flow.spawn(_warm, name=f"{self.name}.prefetch")

    def warmup_programs(
        self, server, example, buckets=None
    ) -> "Dict[str, float]":
        """Drive every (registered tenant x bucket) serving program once
        through `server` (a MicroBatchServer) ahead of traffic: models
        page in through the normal `page_in` funnel and each program
        compiles — or, with an AOT program bank active
        (`config.program_bank_dir`), warm-loads without a trace or
        compile. The store side of the no-compile serving SLA
        (docs/performance.md §12)."""
        return server.warmup(example, tenants=self.keys(), buckets=buckets)

    # -- lifecycle integration ----------------------------------------------
    def promote(self, key: str, arrays: tuple, version: Optional[int] = None):
        """Promote a candidate through `key`'s lifecycle ring (gate +
        canary + version ring), then refresh residency accounting: the
        republish dropped the old constants' tree, so a resident entry
        restages and re-measures under the same compiled plan."""
        entry = self._entry(key)
        if entry.lifecycle is None:
            raise ValueError(f"model {key!r} has no lifecycle attached")
        result = entry.lifecycle.promote(arrays, version=version)
        self.refresh(key)
        return result

    def refresh(self, key: str) -> None:
        """Re-sync accounting after `key`'s arrays changed (republish or
        rollback): recompute the host estimate and, if resident, restage
        the new constants immediately."""
        with self._lock:
            entry = self._entry(key)
            was_resident = entry.resident
            if was_resident:
                self._page_out_locked(key, entry, count_eviction=False)
            entry.est_nbytes = sum(
                _host_nbytes(s._kernel_constants()) for s in entry.stages
            )
            if self._budget is not None and entry.est_nbytes > self._budget:
                raise ModelStoreBudgetExceeded(key, entry.est_nbytes, self._budget)
            if was_resident:
                self.page_in(key)

    # -- introspection -------------------------------------------------------
    def resident_keys(self) -> List[str]:
        with self._lock:
            return [k for k, e in self._entries.items() if e.resident]

    @property
    def budget_bytes(self) -> Optional[int]:
        return self._budget

    @property
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "models": len(self._entries),
                "resident": sum(1 for e in self._entries.values() if e.resident),
                "bytes": self._used,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
            }

    def check_ledger_parity(self) -> None:
        """Assert the store's byte accounting matches the memledger's
        tracked view of every resident constants tree — the same
        invariant DeviceEpochCache pins for epochs."""
        with self._lock:
            tracked = 0
            for entry in self._entries.values():
                if not entry.resident:
                    continue
                for stage in entry.stages:
                    cached = stage.__dict__.get("_device_consts")
                    if cached is not None:
                        tracked += memledger.tracked_nbytes(cached[1])
            if tracked != self._used:
                raise AssertionError(
                    f"{self.name}: ledger parity broken — tracked {tracked} "
                    f"!= accounted {self._used}"
                )
