"""Device-resident epoch cache — the HBM tier over the native data cache.

The reference's bounded-iteration input path is cache-once/replay-every-
epoch (ReplayOperator.java + the spillable DataCache): our port replays to
HOST numpy, so every epoch of a bounded fit re-paid the full host→device
upload. Snap ML (PAPERS.md) names accelerator-resident training-set
caching plus host→device pipelining as the dominant lever for classical-ML
training on accelerators; this module is that lever:

- `DeviceEpochCache` — a keyed LRU of device-resident batch pytrees under
  an HBM budget (`config.device_cache_bytes`, env
  `FLINK_ML_TPU_DEVICE_CACHE_BYTES`; None = unbounded, 0 = disabled).
  Epoch 0 stages each batch ONCE — a single dtype-packed transfer placed
  directly into its data-parallel sharded layout — and epochs >= 1 read
  device-resident shards back with ZERO H2D bytes. Over-budget batches
  are evicted LRU-first; an evicted batch simply remains in the native
  host cache and re-stages (accounted) on its next access, so any budget
  — including 0, the pure re-upload path — computes bit-identical
  results, only the traffic changes. Accounting: `devicecache.hit` /
  `devicecache.miss` / `devicecache.evictBytes`, and the
  `devicecache.bytes` gauge for current residency.

- `CachedEpochLoader` — the cache composed with the shared flow-control
  layer (`flow.BoundedChannel` + `flow.pump`, the same window class the
  Prefetcher and the serving runner ride): hit resolution and miss
  staging both run on ONE pump worker up to `config.
  input_prefetch_depth` batches ahead of the consuming loop, so batch
  b+1's host-cache read + pack + upload overlap batch b's compute, and
  every cache/stager access stays serial by construction (exactly one
  thread ever touches them during an epoch). Results arrive strictly in
  key order; a worker error (including an injected fault inside the
  stage callable) re-raises at the consumer after the batches staged
  before it. A consecutive repeat of the same key (the nb==1
  single-batch stream) is served from the last resolved value even at
  budget 0, preserving the upload-once behavior the hand-rolled loops
  had.

Parity contract (same construction as the dispatch pipeline's chunking
guarantee): caching changes WHEN bytes move, never what is computed — a
cache hit returns the exact device buffers the miss path produced, and
re-staging uploads the same host bytes to the same sharded layout. Pinned
by tests/test_input_pipeline.py across budgets {0, tiny, unbounded}.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, Iterable, Iterator, Optional

from .. import flow
from ..obs import memledger
from ..utils import metrics

__all__ = [
    "DeviceEpochCache",
    "CachedEpochLoader",
    "within_device_budget",
    "cache_contents_section",
    "restore_cache_contents",
]

_UNSET = object()


def within_device_budget(nbytes: int) -> bool:
    """Does a `nbytes` device-resident allocation fit the configured HBM
    cache budget (`config.device_cache_bytes`)? The whole-fit eligibility
    check (parallel/dispatch.whole_fit_plan): a resident program's stacked
    epoch data source must fit where the per-batch cache would have lived.
    None = unbounded budget (fits), 0 = cache disabled (nothing fits)."""
    from .. import config

    if config.device_cache_bytes is None:
        return True
    return int(nbytes) <= int(config.device_cache_bytes)


def _tree_nbytes(tree) -> int:
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree_util.tree_leaves(tree)
    )


# ---------------------------------------------------------------------------
# cache-contents snapshot sections (multi-host sharded snapshots)
# ---------------------------------------------------------------------------
# The ROADMAP item-5 follow-up of PR 6: snapshot the epoch cache's
# CONTENTS, not just its cursor. A stream fit's packed segments — the host
# tier the DeviceEpochCache stages from — travel in the sharded JobSnapshot
# as a `cache` section (spec tag `data`: each simulated host writes its own
# row slice of every segment, ckpt/coordinator.py), written ONCE per job
# key as a *stable* section and reused by reference across snapshot cuts.
# A resumed fit rebuilds its segments from the snapshot and never
# re-consumes the input stream (`restore_cache_contents`).

def cache_contents_section(cache, segs):
    """Materialize the stream cache's packed segments as the host-array
    tuple a snapshot `cache` section stores. Called ONCE, at fit start,
    BEFORE the epoch loader's pump worker exists — the native cache's
    serial-access constraint means snapshot saves inside the training
    loop must never touch it, so the section is captured eagerly and the
    saves close over these arrays (in-memory segments alias the cache's
    own storage; only spilled segments pay a copy)."""
    return tuple(cache.read_array(seg) for seg in segs)


def restore_cache_contents(snap, cache):
    """Rebuild a fresh host cache from a snapshot's `cache` section:
    append every stored segment (replay order) and return the new
    segment ids, or None when the snapshot carries no cache contents —
    the caller then re-ingests from the input stream as before."""
    import numpy as np

    section = snap.sections.get("cache")
    if section is None:
        return None
    segs = [
        cache.append_array(np.ascontiguousarray(np.asarray(arr)))
        for arr in section
    ]
    metrics.inc_counter("devicecache.contents.restored", len(segs))
    return segs


def _release_ledger_entries(entries) -> None:
    for item in entries.values():
        memledger.release(item[2])
    entries.clear()


class DeviceEpochCache:
    """Keyed LRU of device-resident batch pytrees under an HBM budget.

    Residency is ownership-accounted in the HBM ledger
    (obs/memledger.py): every insert opens a `batchCache` entry, every
    evict/replace/clear closes it, so the ledger's `batchCache` live
    bytes and this cache's `devicecache.bytes` gauge are equal after ANY
    hit/miss/evict sequence — `check_ledger_parity` pins the invariant
    (tests/test_memledger.py runs it after adversarial sequences)."""

    def __init__(self, budget_bytes=_UNSET):
        if budget_bytes is _UNSET:
            from .. import config

            budget_bytes = config.device_cache_bytes
        self.budget_bytes: Optional[int] = (
            None if budget_bytes is None else max(0, int(budget_bytes))
        )
        # key -> (tree, nbytes, ledger handle)
        self._entries: "OrderedDict[Hashable, tuple]" = OrderedDict()
        self._used = 0
        # a cache dropped without clear() (a fit abandoning its loader)
        # must not strand its ledger entries: the finalizer closes any
        # still open when the cache object itself is collected
        weakref.finalize(self, _release_ledger_entries, self._entries)

    @property
    def enabled(self) -> bool:
        return self.budget_bytes is None or self.budget_bytes > 0

    def get(self, key: Hashable):
        """The cached pytree for `key`, or None (counted as hit/miss)."""
        entry = self._entries.get(key)
        if entry is None:
            metrics.inc_counter("devicecache.miss")
            return None
        self._entries.move_to_end(key)  # LRU freshness
        metrics.inc_counter("devicecache.hit")
        return entry[0]

    def put(self, key: Hashable, tree) -> bool:
        """Cache `tree` under `key`, evicting LRU entries while over
        budget. Returns False when the budget excludes the entry outright
        (budget 0, or a single batch larger than the whole budget) — the
        caller's device arrays stay usable either way."""
        nbytes = _tree_nbytes(tree)
        if self.budget_bytes is not None and nbytes > self.budget_bytes:
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self._used -= old[1]
            memledger.release(old[2])
            # a replaced entry's bytes left residency exactly as an
            # evicted entry's do — count them, or gauge+evictBytes
            # under-reports the bytes that ever left the cache
            metrics.inc_counter("devicecache.replaceBytes", old[1])
        handle = memledger.register("batchCache", nbytes)
        self._entries[key] = (tree, nbytes, handle)
        self._used += nbytes
        while self.budget_bytes is not None and self._used > self.budget_bytes:
            _, (_, evicted, ev_handle) = self._entries.popitem(last=False)
            self._used -= evicted
            memledger.release(ev_handle)
            metrics.inc_counter("devicecache.evict")
            metrics.inc_counter("devicecache.evictBytes", evicted)
        metrics.set_gauge("devicecache.bytes", self._used)
        return True

    def clear(self) -> None:
        for _, _, handle in self._entries.values():
            memledger.release(handle)
        self._entries.clear()
        self._used = 0
        metrics.set_gauge("devicecache.bytes", 0)

    def check_ledger_parity(self) -> None:
        """Assert ledger `batchCache` live bytes == this cache's own
        accounting (raises AssertionError naming both sides). Exact only
        while this is the sole live DeviceEpochCache — the ledger
        category is process-wide."""
        ledgered = memledger.live_bytes("batchCache")
        assert ledgered == self._used, (
            f"ledger batchCache={ledgered} != devicecache bytes={self._used}"
        )

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def stats(self) -> Dict[str, int]:
        return {
            "entries": len(self._entries),
            "residentBytes": self._used,
            "budgetBytes": -1 if self.budget_bytes is None else self.budget_bytes,
        }


class CachedEpochLoader:
    """Serve keyed batches from the device cache, staging misses through
    a bounded-depth single-worker pump (`flow.BoundedChannel`).

    `stage(key)` (caller-supplied) does the miss work: read the batch
    from the host cache, pack it, and upload it via the accounted stager.
    Hit lookup, miss staging and the LRU `put` all run on the ONE pump
    worker, so the native cache's serial-access constraint — and the
    device cache's internal state — are single-threaded by construction.
    `epoch(keys)` yields the device pytrees in key order; every resolved
    batch travels through the channel as a strong reference, so an
    eviction between staging and consumption cannot drop it. A
    consecutive repeat of the same key reuses the last resolved tree
    with no cache lookup and no re-upload (the nb == 1 single-batch
    stream), cache enabled or not.
    """

    def __init__(
        self,
        stage: Callable[[Hashable], Any],
        cache: Optional[DeviceEpochCache] = None,
        depth: Optional[int] = None,
    ):
        from .. import config

        self.stage = stage
        self.cache = cache if cache is not None else DeviceEpochCache()
        self.depth = max(
            1, int(depth if depth is not None else config.input_prefetch_depth)
        )
        self._last: Optional[tuple] = None  # (key, tree) most recently resolved
        self.watchdog = flow.StragglerWatchdog("devicecache.stage")

    def _resolve(self, key: Hashable):
        """Worker-side hit/miss resolution for one key (serial: one pump
        worker is the only thread that ever calls this per epoch)."""
        if self._last is not None and self._last[0] == key:
            return self._last[1]  # consecutive repeat: no lookup, no upload
        tree = self.cache.get(key) if self.cache.enabled else None
        if tree is None:
            with self.watchdog.observe():
                tree = self.stage(key)
            self.cache.put(key, tree)
        self._last = (key, tree)
        return tree

    def epoch(self, keys: Iterable[Hashable]) -> Iterator:
        """Yield the device batch for each key in order, resolving up to
        `depth` keys ahead on the pump worker. Closing the generator
        early (a tol stop) cancels the speculative staging; a stage error
        re-raises here, after the batches resolved before it."""
        metrics.set_gauge("prefetch.depth", self.depth)
        channel = flow.BoundedChannel(self.depth, policy=flow.BLOCK, name="devicecache.stage")
        flow.pump(keys, channel, transform=self._resolve)
        try:
            yield from channel
        finally:
            channel.cancel()
