"""tpulint — AST-based static analysis for TPU dispatch hazards.

BENCH_r05's verdict is that the train loop is host-dispatch-bound: the
hazard classes that put it there (hidden host syncs, per-call retraces,
unaccounted transfers, donated-buffer reuse, unstageable checkpoint tags)
are all *source-level* mistakes that a profiler only catches after a
regression ships. This package holds them statically instead:

- ``source``  — the shared source model (raw text, comment/string-stripped
  text, AST, ``# tpulint: disable=`` suppressions). The four legacy gate
  scripts' duplicated ``_code_only`` helpers live here now, once.
- ``engine``  — rule registry, project scanner, suppression resolution
  (an unused suppression is itself a finding), report formatting.
- ``rules/``  — one module per hazard family; each rule carries its own
  documentation (``id``, ``title``, ``rationale``, example).

Run via ``scripts/tpulint.py`` (or ``python -m pytest
tests/test_tpulint.py`` which keeps the zero-unsuppressed-findings
contract in tier-1). The catalogue is documented in
docs/static_analysis.md.
"""

from .engine import Finding, Project, all_rules, get_rule, run  # noqa: F401

__all__ = ["Finding", "Project", "all_rules", "get_rule", "run"]
