"""Abstract sharding interpreter — the SPMD layer under tpulint v3.

ROADMAP item 1 rebuilds ``parallel/mesh.py`` into a ``(data, feature)``
2D mesh with feature-sharded weights and axis-restricted collectives.
That is the class of change where a wrong axis name, a collective under
a rank-dependent branch, or an ``out_specs`` that lies about a reduction
produces *silent numeric corruption* or a *multi-host deadlock* — not a
test failure. This module gives the rule layer the semantic facts those
hazards are made of:

- **Axis registry** (:func:`axis_registry`): every mesh-axis constant
  declared in ``parallel/mesh.py`` (``DATA_AXIS = "data"``-style
  module-level string assigns) plus every axis literal a ``create_mesh``
  / ``Mesh`` construction introduces. The constants are the single
  source of truth for axis names; a literal that matches one is a
  *constant bypass*, a literal that matches none is an *unknown axis*.
- **Collective index** (:func:`collective_index`): the accounted wrapper
  functions in ``parallel/collectives.py`` (any module-level def with an
  ``axis_name`` parameter), classified as ``reduce`` / ``gather`` /
  ``permute`` / ``index`` by the raw ``lax`` primitive in their body
  (name-based fallback), with the axis parameter's position and default.
  Raw ``lax.psum``-family calls are indexed too, so the interpreter sees
  collectives with or without the wrapper layer.
- **Spec parsing** (:func:`parse_spec_expr`): ``PartitionSpec`` /
  ``P(...)`` expressions to abstract per-dim axis tuples, following
  local names one assignment deep (the ``batched = P(None, axis, None)``
  idiom) and resolving axis constants through module aliases
  (``mesh_lib.DATA_AXIS``).
- **The interpreter** (:class:`BodyInterpreter`): walks each
  ``shard_map``-ped body with an abstract value per name — the set of
  mesh axes the value *varies over* (sharded data, per-shard partial
  sums, ``axis_index`` results), or ``unknown`` when a spec could not be
  resolved (unknown suppresses findings; the engine under-approximates,
  same discipline as the taint walker). Collectives transform the
  variance set (a reduce/gather over axis *a* makes the result uniform
  along *a*); ``lax.while_loop``/``cond``/``scan`` bodies are run to a
  small join fixpoint before one recording pass; local and one-hop
  cross-module calls are interpreted inline (bounded depth), unknown
  calls join their arguments' variance.

Everything is exposed as one memoized :class:`SpmdInterpretation` per
project (``project.index("spmd", interpret)``) holding typed
:class:`SpmdEvent` records; the four v3 rules (``mesh-axis``,
``collective-divergence``, ``spec-consistency``,
``precision-determinism`` in ``rules/``) are thin filters over the
event stream, so all four agree on what a collective, an axis, and a
spec are. docs/static_analysis.md carries the rule catalogue and the
2D-mesh readiness checklist this gates.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .source import SourceModule, dotted_name

MESH_PATH = "flink_ml_tpu/parallel/mesh.py"
COLLECTIVES_PATH = "flink_ml_tpu/parallel/collectives.py"

#: modules whose hand-rolled reduction folds are the sanctioned,
#: replica-order bit-exact implementations (the ring fold and the sparse
#: scatter-partial fold live here; anywhere else a manual fold over
#: collective results reassociates the sum)
SANCTIONED_FOLD_PATHS = (COLLECTIVES_PATH, "flink_ml_tpu/parallel/overlap.py")

#: raw lax primitive -> (kind, axis positional index)
LAX_COLLECTIVES = {
    "psum": ("reduce", 1),
    "pmean": ("reduce", 1),
    "pmax": ("reduce", 1),
    "pmin": ("reduce", 1),
    "psum_scatter": ("reduce", 1),
    "all_gather": ("gather", 1),
    "all_to_all": ("gather", 1),
    "ppermute": ("permute", 1),
    "axis_index": ("index", 0),
    "axis_size": ("size", 0),
}

#: body-scan classification priority (a wrapper whose body mixes
#: primitives is named for the strongest semantic it applies)
_KIND_PRIORITY = ("reduce", "gather", "permute", "index", "size")

#: wrapper-name fallbacks when the body gives no primitive away
WRAPPER_NAME_KINDS = (
    ("all_reduce", "reduce"),
    ("reduce_scatter", "reduce"),
    ("sparse_all_reduce", "reduce"),
    ("all_gather", "gather"),
    ("ppermute", "permute"),
    ("axis_index", "index"),
    ("axis_size", "size"),
)

#: dtypes whose use as an accumulator/reduction operand narrows any
#: float32 operand — the implicit-downcast-before-psum hazard
NARROW_DTYPES = {"bfloat16", "float16", "int8", "uint8", "float8_e4m3fn", "float8_e5m2"}

#: sentinel for "could not resolve" — suppresses findings downstream
UNKNOWN = object()

#: bounded interpretation depth for inlined calls
MAX_DEPTH = 4
#: join-fixpoint iterations for loop carries before the recording pass
FIXPOINT_ROUNDS = 3


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SpmdEvent:
    """One semantic fact the rules turn into findings.

    Kinds:
      ``unknown-axis``        — axis literal not declared by any mesh constant
      ``axis-bypass``         — axis literal that duplicates a named constant
      ``unsharded-collective``— gather/permute over an axis the operand does
                                not vary on
      ``divergent-collective``— collective reachable under a shard-varying
                                branch inside a shard_map body
      ``double-reduce``       — reduction over an axis the operand is already
                                uniform on (double-counting)
      ``unreduced-output``    — out_spec declares replicated but the returned
                                value still varies over mesh axes
      ``spec-arity``          — in_specs arity does not match the body params
      ``downcast-before-reduce`` — narrowed dtype feeds a reduction
      ``order-fold``          — manual accumulation of permuted shards outside
                                the sanctioned ring fold
    """

    path: str
    line: int
    kind: str
    detail: str = ""  # axis name / op name / dtype, rule-specific
    extra: Tuple = ()  # structured payload (site line, branch line, ...)


# ---------------------------------------------------------------------------
# axis registry
# ---------------------------------------------------------------------------

@dataclass
class AxisRegistry:
    #: (module_name, NAME) -> axis string, e.g. (…parallel.mesh, DATA_AXIS)
    constants: Dict[Tuple[str, str], str] = field(default_factory=dict)
    #: axis string -> constant NAME (for bypass messages)
    by_value: Dict[str, str] = field(default_factory=dict)
    #: every axis name any mesh declaration can produce
    known_axes: Set[str] = field(default_factory=set)

    def constant_value(self, module_name: str, name: str) -> Optional[str]:
        return self.constants.get((module_name, name))


def _build_axis_registry(project) -> AxisRegistry:
    reg = AxisRegistry()
    mesh = project.module_at(MESH_PATH)
    for source_mod in (mesh, project.module_at(COLLECTIVES_PATH)):
        if source_mod is None or source_mod.tree is None:
            continue
        for node in source_mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.endswith("_AXIS")
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                name, value = node.targets[0].id, node.value.value
                reg.constants[(source_mod.module_name, name)] = value
                reg.by_value.setdefault(value, name)
                reg.known_axes.add(value)
    # re-exports: `from .mesh import DATA_AXIS` binds the constant in the
    # importing module under the same (or aliased) name
    for module in project.modules:
        if module.tree is None:
            continue
        from .source import resolve_relative_import

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            target = resolve_relative_import(
                module.module_name, node, module.is_package
            )
            if target is None:
                continue
            for alias in node.names:
                value = reg.constants.get((target, alias.name))
                if value is not None:
                    bound = alias.asname or alias.name
                    reg.constants[(module.module_name, bound)] = value
    return reg


def axis_registry(project) -> AxisRegistry:
    return project.index("spmd-axes", _build_axis_registry)


# ---------------------------------------------------------------------------
# collective index
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CollectiveWrapper:
    name: str
    kind: str  # reduce | gather | permute | index
    axis_param: int  # positional index of axis_name in the signature
    default_axis: Optional[str]  # resolved default, None when required/unknown
    operand_params: Tuple[int, ...] = (0,)  # positions of reduced operands


def _wrapper_kind(name: str, node: ast.FunctionDef) -> Optional[str]:
    # the wrapper NAME is the API contract — classify by it first
    # (all_reduce_sum_chunked's body opens with axis_size, not psum)
    for prefix, kind in WRAPPER_NAME_KINDS:
        if name.lstrip("_").startswith(prefix):
            return kind
    found: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            called = dotted_name(sub.func)
            if called is None:
                continue
            base = called.split(".")[-1]
            if base in LAX_COLLECTIVES:
                found.add(LAX_COLLECTIVES[base][0])
    for kind in _KIND_PRIORITY:
        if kind in found:
            return kind
    return None


def _build_collective_index(project) -> Dict[str, CollectiveWrapper]:
    out: Dict[str, CollectiveWrapper] = {}
    module = project.module_at(COLLECTIVES_PATH)
    if module is None or module.tree is None:
        return out
    reg = axis_registry(project)
    for node in module.tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        params = [a.arg for a in node.args.args]
        if "axis_name" not in params:
            continue
        kind = _wrapper_kind(node.name, node)
        if kind is None:
            continue
        axis_param = params.index("axis_name")
        default_axis = None
        defaults = node.args.defaults
        if defaults:
            offset = len(params) - len(defaults)
            if axis_param >= offset:
                default = defaults[axis_param - offset]
                if isinstance(default, ast.Constant) and isinstance(
                    default.value, str
                ):
                    default_axis = default.value
                elif isinstance(default, ast.Name):
                    default_axis = reg.constant_value(
                        module.module_name, default.id
                    )
        operands: Tuple[int, ...] = (0,)
        if node.name == "sparse_all_reduce_sum":
            operands = (0, 1)
        out[node.name] = CollectiveWrapper(
            name=node.name,
            kind=kind,
            axis_param=axis_param,
            default_axis=default_axis,
            operand_params=operands,
        )
    return out


def collective_index(project) -> Dict[str, CollectiveWrapper]:
    return project.index("spmd-collectives", _build_collective_index)


# ---------------------------------------------------------------------------
# per-module resolution context
# ---------------------------------------------------------------------------

class ModuleContext:
    """Resolution facts for one module: jit/alias info, the axis
    registry, and which local names denote the collective wrappers."""

    def __init__(self, project, module: SourceModule):
        from .rules import _jitindex

        self.project = project
        self.module = module
        self.info = _jitindex.jit_index(project)[module.path]
        self.axes = axis_registry(project)
        self.wrappers = collective_index(project)
        self.is_collectives_module = module.path == COLLECTIVES_PATH

    # -- collective call recognition ----------------------------------------
    def collective_for(self, call: ast.Call) -> Optional[Tuple[str, str, int, Optional[str], Tuple[int, ...]]]:
        """``(op_name, kind, axis_param, default_axis, operand_params)``
        when ``call`` is a collective — a wrapper from collectives.py
        (called locally, via a from-import, or via a module alias) or a
        raw ``lax`` primitive."""
        name = dotted_name(call.func)
        if name is None:
            return None
        root, _, rest = name.partition(".")
        base = name.split(".")[-1]
        # raw lax primitive
        if rest and root in self.info.lax_aliases and base in LAX_COLLECTIVES:
            kind, axis_param = LAX_COLLECTIVES[base]
            return (base, kind, axis_param, None, (0,))
        if (
            not rest
            and base in LAX_COLLECTIVES
            and self._imported_from(base, "jax.lax")
        ):
            kind, axis_param = LAX_COLLECTIVES[base]
            return (base, kind, axis_param, None, (0,))
        # wrapper, by any route that reaches collectives.py
        if base in self.wrappers and self._names_wrapper(name, base):
            w = self.wrappers[base]
            return (w.name, w.kind, w.axis_param, w.default_axis, w.operand_params)
        return None

    def _imported_from(self, bound: str, target_module: str) -> bool:
        entry = self.info.imports.get(bound)
        return entry is not None and entry[0] == target_module

    def _names_wrapper(self, name: str, base: str) -> bool:
        if self.is_collectives_module and name == base:
            return True
        root, _, rest = name.partition(".")
        if not rest:
            entry = self.info.imports.get(name)
            return entry is not None and (
                entry[0] == "flink_ml_tpu.parallel.collectives"
                or entry[0].endswith("parallel.collectives")
            )
        entry = self.info.imports.get(root)
        if entry is None:
            return False
        dotted = f"{entry[0]}.{entry[1]}"
        return dotted.endswith("parallel.collectives")

    # -- axis expression resolution -----------------------------------------
    def resolve_axis(
        self, node: ast.AST, local_env: Optional[Dict[str, ast.AST]] = None
    ):
        """``("literal", value, line)`` for a string literal,
        ``("const", value)`` when the expression denotes a declared axis
        constant, else None (parameter / unresolvable)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return ("literal", node.value, node.lineno)
        if isinstance(node, ast.Name):
            value = self.axes.constant_value(self.module.module_name, node.id)
            if value is not None:
                return ("const", value)
            if local_env and node.id in local_env:
                target = local_env[node.id]
                if target is not node:
                    return self.resolve_axis(target, None)
            return None
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            entry = self.info.imports.get(node.value.id)
            if entry is not None:
                target_module = f"{entry[0]}.{entry[1]}"
                value = self.axes.constants.get((target_module, node.attr))
                if value is None:
                    value = self.axes.constants.get((entry[0], node.attr))
                if value is not None:
                    return ("const", value)
        return None


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------

def _is_partition_spec_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is None:
        return False
    base = name.split(".")[-1]
    return base in ("P", "PartitionSpec")


def parse_spec_expr(
    ctx: ModuleContext, node: ast.AST, local_env: Dict[str, ast.AST]
):
    """Parse a specs expression into the abstract form the interpreter
    consumes: a ``P(...)`` call becomes a tuple of per-dim entries (axis
    string, None, or UNKNOWN); a tuple/list of specs becomes a tuple of
    parsed specs; anything unresolvable is UNKNOWN."""
    if isinstance(node, ast.Name) and node.id in local_env:
        target = local_env[node.id]
        if target is not node:
            return parse_spec_expr(ctx, target, local_env)
        return UNKNOWN
    if _is_partition_spec_call(node):
        entries: List = []
        for arg in node.args:
            if isinstance(arg, ast.Constant) and arg.value is None:
                entries.append(None)
                continue
            if isinstance(arg, (ast.Tuple, ast.List)):
                sub = []
                for elt in arg.elts:
                    resolved = ctx.resolve_axis(elt, local_env)
                    sub.append(resolved[1] if resolved else UNKNOWN)
                entries.append(tuple(sub))
                continue
            resolved = ctx.resolve_axis(arg, local_env)
            entries.append(resolved[1] if resolved else UNKNOWN)
        return ("spec", tuple(entries))
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(parse_spec_expr(ctx, elt, local_env) for elt in node.elts)
    return UNKNOWN


def spec_axes(spec) -> Optional[FrozenSet[str]]:
    """Axes a parsed spec shards over; None when the spec is UNKNOWN
    anywhere (suppresses downstream findings)."""
    if spec is UNKNOWN:
        return None
    if isinstance(spec, tuple) and spec and spec[0] == "spec":
        axes: Set[str] = set()
        for entry in spec[1]:
            if entry is None:
                continue
            if entry is UNKNOWN:
                return None
            if isinstance(entry, tuple):
                for sub in entry:
                    if sub is UNKNOWN:
                        return None
                    axes.add(sub)
            else:
                axes.add(entry)
        return frozenset(axes)
    return None


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class AbsVal:
    """What the interpreter knows about a value: the mesh axes it varies
    over (empty = uniform across every shard), whether anything along
    the way was unresolvable (unknown poisons — no findings), and
    provenance flags for the precision rule."""

    axes: FrozenSet[str] = frozenset()
    unknown: bool = False
    narrowed: Optional[str] = None  # dtype name set by a narrowing astype
    permuted: bool = False  # derives from a ppermute result

    def join(self, other: "AbsVal") -> "AbsVal":
        return AbsVal(
            axes=self.axes | other.axes,
            unknown=self.unknown or other.unknown,
            narrowed=self.narrowed or other.narrowed,
            permuted=self.permuted or other.permuted,
        )


UNIFORM = AbsVal()
UNKNOWN_VAL = AbsVal(unknown=True)


class TupleVal:
    """Tuple-structured abstract value (loop carries, multi-returns)."""

    __slots__ = ("elts",)

    def __init__(self, elts: Sequence):
        self.elts = list(elts)

    def collapse(self) -> AbsVal:
        out = UNIFORM
        for e in self.elts:
            out = out.join(e.collapse() if isinstance(e, TupleVal) else e)
        return out

    def join(self, other):
        if isinstance(other, TupleVal) and len(other.elts) == len(self.elts):
            return TupleVal(
                [_join(a, b) for a, b in zip(self.elts, other.elts)]
            )
        return self.collapse().join(
            other.collapse() if isinstance(other, TupleVal) else other
        )

    def __eq__(self, other):
        return isinstance(other, TupleVal) and self.elts == other.elts

    def __hash__(self):  # pragma: no cover - not used as dict key
        return hash(tuple(self.elts))


def _join(a, b):
    if isinstance(a, TupleVal):
        return a.join(b)
    if isinstance(b, TupleVal):
        return b.join(a)
    return a.join(b)


def _scalar(v) -> AbsVal:
    return v.collapse() if isinstance(v, TupleVal) else v


def spec_to_absval(spec) -> object:
    """Abstract value of a parameter bound with ``spec``."""
    if spec is UNKNOWN:
        return UNKNOWN_VAL
    if isinstance(spec, tuple) and spec and spec[0] == "spec":
        axes = spec_axes(spec)
        if axes is None:
            return UNKNOWN_VAL
        return AbsVal(axes=axes)
    if isinstance(spec, tuple):  # tuple of specs -> tuple-structured param
        return TupleVal([spec_to_absval(s) for s in spec])
    return UNKNOWN_VAL


# attribute reads returning host metadata (uniform across shards)
_META_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize", "sharding"}


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

class BodyInterpreter:
    """Abstract walk of one function body under per-shard semantics.

    ``record=False`` runs a join pass (loop-carry fixpointing) without
    emitting events; the driver runs a few join rounds, then one
    recording pass against the stabilized environment.
    """

    def __init__(
        self,
        ctx: ModuleContext,
        fn: ast.FunctionDef,
        arg_vals: Sequence,
        events: List[SpmdEvent],
        local_env: Dict[str, ast.AST],
        depth: int = 0,
        record: bool = True,
        divergent: Optional[Tuple[int, str]] = None,
        seen: Optional[Set[Tuple[str, str]]] = None,
        closure_env: Optional[Dict[str, object]] = None,
        closure_defs: Optional[Dict[str, ast.FunctionDef]] = None,
    ):
        self.ctx = ctx
        self.fn = fn
        self.events = events
        self.local_env = local_env
        self.depth = depth
        self.record = record
        #: (branch line, reason) when inside a shard-varying branch
        self.divergent = divergent
        self.seen = seen if seen is not None else set()
        # lexical scoping: a nested def (branch fn, local helper) reads its
        # enclosing scope's names — seed from the parent env, params shadow
        self.env: Dict[str, object] = dict(closure_env or {})
        params = [a.arg for a in fn.args.args]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        for i, name in enumerate(params):
            self.env[name] = arg_vals[i] if i < len(arg_vals) else UNKNOWN_VAL
        self.returns: List[Tuple[object, int]] = []
        self._local_defs = dict(closure_defs or {})
        self._local_defs.update(
            {n.name: n for n in ast.walk(fn) if isinstance(n, ast.FunctionDef)}
        )

    # -- events -------------------------------------------------------------
    def emit(self, line: int, kind: str, detail: str = "", extra: Tuple = ()):
        if self.record:
            self.events.append(
                SpmdEvent(
                    path=self.ctx.module.path,
                    line=line,
                    kind=kind,
                    detail=detail,
                    extra=extra,
                )
            )

    # -- evaluation ---------------------------------------------------------
    def eval(self, node: ast.AST):
        if node is None:
            return UNIFORM
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            return UNIFORM  # closure constants / hyperparams: uniform
        if isinstance(node, ast.Constant):
            return UNIFORM
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, ast.Attribute):
            if node.attr in _META_ATTRS:
                return UNIFORM
            return _scalar(self.eval(node.value))
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value)
            if isinstance(base, TupleVal):
                idx = node.slice
                if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
                    if -len(base.elts) <= idx.value < len(base.elts):
                        return base.elts[idx.value]
                return base.collapse()
            return _scalar(base)
        if isinstance(node, ast.BinOp):
            return _scalar(self.eval(node.left)).join(_scalar(self.eval(node.right)))
        if isinstance(node, ast.BoolOp):
            out = UNIFORM
            for v in node.values:
                out = out.join(_scalar(self.eval(v)))
            return out
        if isinstance(node, ast.UnaryOp):
            return _scalar(self.eval(node.operand))
        if isinstance(node, ast.Compare):
            out = _scalar(self.eval(node.left))
            for comp in node.comparators:
                out = out.join(_scalar(self.eval(comp)))
            return out
        if isinstance(node, ast.IfExp):
            return _join(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            return TupleVal([self.eval(e) for e in node.elts])
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.NamedExpr):
            value = self.eval(node.value)
            self.assign(node.target, value)
            return value
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            out = UNIFORM
            for gen in node.generators:
                out = out.join(_scalar(self.eval(gen.iter)))
            return out
        return UNIFORM

    # -- calls --------------------------------------------------------------
    def eval_call(self, call: ast.Call):
        collective = self.ctx.collective_for(call)
        if collective is not None:
            return self.apply_collective(call, collective)

        name = dotted_name(call.func)
        arg_vals = [_scalar(self.eval(a)) for a in call.args] + [
            _scalar(self.eval(kw.value)) for kw in call.keywords
        ]
        joined = UNIFORM
        for v in arg_vals:
            joined = joined.join(v)

        # control-flow primitives with function operands
        if name is not None:
            base = name.split(".")[-1]
            root, _, rest = name.partition(".")
            is_lax = (rest and root in self.ctx.info.lax_aliases) or (
                not rest and self.ctx._imported_from(base, "jax.lax")
            )
            if is_lax and base == "while_loop" and len(call.args) >= 3:
                return self.apply_while_loop(call)
            if is_lax and base == "fori_loop" and len(call.args) >= 4:
                return self.apply_fori_loop(call)
            if is_lax and base == "cond" and len(call.args) >= 3:
                return self.apply_cond(call)
            if is_lax and base == "switch" and len(call.args) >= 2:
                return self.apply_switch(call)
            if is_lax and base == "scan" and len(call.args) >= 2:
                return self.apply_scan(call)

        # .astype(narrow) marks provenance for the precision rule
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "astype"
            and call.args
        ):
            target = self._dtype_name(call.args[0])
            base_val = _scalar(self.eval(call.func.value))
            if target in NARROW_DTYPES:
                return AbsVal(
                    axes=base_val.axes,
                    unknown=base_val.unknown,
                    narrowed=target,
                    permuted=base_val.permuted,
                )
            return base_val

        # local nested function: interpret inline with this scope as its
        # closure (bounded)
        if isinstance(call.func, ast.Name) and call.func.id in self._local_defs:
            return self._interpret_local(
                self._local_defs[call.func.id],
                [self.eval(a) for a in call.args],
            )

        # cross-module / module-level function via the call graph
        resolved = self._resolve_cross(call)
        if resolved is not None:
            decl, target_ctx, skip_self = resolved
            args = [self.eval(a) for a in call.args]
            return self.interpret_callee(decl.node, args, target_ctx)

        # unknown call: variance joins through (conservative propagation)
        return joined

    def _dtype_name(self, node: ast.AST) -> Optional[str]:
        name = dotted_name(node)
        if name is not None:
            return name.split(".")[-1]
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        return None

    def _resolve_cross(self, call: ast.Call):
        if self.depth >= MAX_DEPTH:
            return None
        from . import callgraph

        graph = callgraph.get(self.ctx.project)
        resolved = graph.resolve(self.ctx.module, call.func, None)
        if resolved is None:
            return None
        decl, skip_self = resolved
        key = (decl.path, decl.qualname)
        if key in self.seen:
            return None
        target_module = self.ctx.project.module_at(decl.path)
        if target_module is None:
            return None
        target_ctx = (
            self.ctx
            if target_module is self.ctx.module
            else ModuleContext(self.ctx.project, target_module)
        )
        return decl, target_ctx, skip_self

    def _interpret_local(self, fn: ast.FunctionDef, args: Sequence):
        key = (self.ctx.module.path, fn.name)
        if self.depth >= MAX_DEPTH or key in self.seen:
            joined = UNIFORM
            for a in args:
                joined = joined.join(_scalar(a))
            return joined
        sub = BodyInterpreter(
            ctx=self.ctx,
            fn=fn,
            arg_vals=args,
            events=self.events,
            local_env=self.local_env,
            depth=self.depth + 1,
            record=self.record,
            divergent=self.divergent,
            seen=self.seen | {key},
            closure_env=self.env,
            closure_defs=self._local_defs,
        )
        sub.run(fn.body)
        return sub.return_value(args)

    def interpret_callee(self, fn: ast.FunctionDef, args: Sequence, ctx):
        key = (ctx.module.path, fn.name)
        if self.depth >= MAX_DEPTH or key in self.seen:
            joined = UNIFORM
            for a in args:
                joined = joined.join(_scalar(a))
            return joined
        sub = BodyInterpreter(
            ctx=ctx,
            fn=fn,
            arg_vals=args,
            events=self.events,
            local_env=self.local_env if ctx is self.ctx else {},
            depth=self.depth + 1,
            record=self.record and ctx.module is self.ctx.module,
            divergent=self.divergent,
            seen=self.seen | {key},
        )
        sub.run(fn.body)
        return sub.return_value(args)

    def return_value(self, args: Sequence):
        if not self.returns:
            joined = UNIFORM
            for a in args:
                joined = joined.join(_scalar(a))
            return joined
        out = self.returns[0][0]
        for v, _ in self.returns[1:]:
            out = _join(out, v)
        return out

    # -- collectives --------------------------------------------------------
    def apply_collective(self, call: ast.Call, collective):
        op, kind, axis_param, default_axis, operand_params = collective
        axis = self._collective_axis(call, axis_param, default_axis)
        operand = UNIFORM
        for pos in operand_params:
            if pos < len(call.args):
                operand = operand.join(_scalar(self.eval(call.args[pos])))
        # evaluate remaining args for their side effects on env
        for i, a in enumerate(call.args):
            if i not in operand_params and i != axis_param:
                self.eval(a)

        if self.divergent is not None and kind in ("reduce", "gather", "permute"):
            branch_line, reason = self.divergent
            self.emit(
                call.lineno,
                "divergent-collective",
                op,
                extra=(branch_line, reason, axis or "?"),
            )

        if kind == "size":
            return UNIFORM  # static participant count, same on every shard
        if kind == "index":
            return AbsVal(axes=frozenset({axis}) if axis else frozenset())

        if axis is None or operand.unknown:
            # unresolvable axis or unknown operand: keep the variance flow
            # honest but emit nothing
            if kind in ("reduce", "gather"):
                return AbsVal(unknown=operand.unknown)
            return operand

        if kind == "reduce":
            if axis not in operand.axes:
                self.emit(call.lineno, "double-reduce", op, extra=(axis,))
            if operand.narrowed:
                self.emit(
                    call.lineno,
                    "downcast-before-reduce",
                    op,
                    extra=(operand.narrowed,),
                )
            return AbsVal(axes=operand.axes - {axis})
        if kind == "gather":
            if axis not in operand.axes:
                self.emit(call.lineno, "unsharded-collective", op, extra=(axis,))
            return AbsVal(axes=operand.axes - {axis}, narrowed=operand.narrowed)
        if kind == "permute":
            if axis not in operand.axes:
                self.emit(call.lineno, "unsharded-collective", op, extra=(axis,))
            return AbsVal(
                axes=operand.axes | ({axis} if axis else frozenset()),
                narrowed=operand.narrowed,
                permuted=True,
            )
        return operand

    def _collective_axis(
        self, call: ast.Call, axis_param: int, default_axis: Optional[str]
    ) -> Optional[str]:
        node = None
        if axis_param < len(call.args):
            node = call.args[axis_param]
        else:
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis"):
                    node = kw.value
                    break
        if node is None:
            return default_axis
        resolved = self.ctx.resolve_axis(node, self.local_env)
        if resolved is None:
            # a Name bound inside this body (e.g. unpacked) — try env-free
            # local assignment table built by the site scanner
            return None
        return resolved[1]

    # -- structured control flow --------------------------------------------
    def _branch_fn(self, node: ast.AST) -> Optional[ast.FunctionDef]:
        if isinstance(node, ast.Name):
            return self._local_defs.get(node.id)
        if isinstance(node, ast.Lambda):
            return None
        return None

    def _run_branch_fn(self, fn, args, divergent):
        sub = BodyInterpreter(
            ctx=self.ctx,
            fn=fn,
            arg_vals=args,
            events=self.events,
            local_env=self.local_env,
            depth=self.depth + 1,
            record=self.record,
            divergent=divergent,
            seen=self.seen | {(self.ctx.module.path, fn.name)},
            closure_env=self.env,
            closure_defs=self._local_defs,
        )
        sub.run(fn.body)
        return sub.return_value(args)

    def apply_while_loop(self, call: ast.Call):
        cond_fn = self._branch_fn(call.args[0])
        body_fn = self._branch_fn(call.args[1])
        carry = self.eval(call.args[2])
        if body_fn is None:
            return _scalar(carry)
        # join-fixpoint the carry silently, then one recording pass
        for _ in range(FIXPOINT_ROUNDS):
            nxt = self._run_quiet(body_fn, [carry])
            joined = _join(carry, nxt)
            if joined == carry:
                break
            carry = joined
        divergent = self.divergent
        if cond_fn is not None:
            pred = _scalar(self._run_quiet(cond_fn, [carry]))
            if pred.axes and not pred.unknown:
                divergent = (call.lineno, "while_loop predicate varies per shard")
        out = self._run_branch_fn(body_fn, [carry], divergent)
        return _join(carry, out)

    def apply_fori_loop(self, call: ast.Call):
        body_fn = self._branch_fn(call.args[2])
        carry = self.eval(call.args[3])
        bounds = _scalar(self.eval(call.args[0])).join(
            _scalar(self.eval(call.args[1]))
        )
        if body_fn is None:
            return _scalar(carry)
        for _ in range(FIXPOINT_ROUNDS):
            nxt = self._run_quiet(body_fn, [UNIFORM, carry])
            joined = _join(carry, nxt)
            if joined == carry:
                break
            carry = joined
        divergent = self.divergent
        if bounds.axes and not bounds.unknown:
            divergent = (call.lineno, "fori_loop bounds vary per shard")
        out = self._run_branch_fn(body_fn, [UNIFORM, carry], divergent)
        return _join(carry, out)

    def apply_cond(self, call: ast.Call):
        pred = _scalar(self.eval(call.args[0]))
        operands = [self.eval(a) for a in call.args[3:]]
        divergent = self.divergent
        if pred.axes and not pred.unknown:
            divergent = (call.lineno, "cond predicate varies per shard")
        out = None
        for branch_arg in call.args[1:3]:
            fn = self._branch_fn(branch_arg)
            if fn is None:
                continue
            res = self._run_branch_fn(fn, operands, divergent)
            out = res if out is None else _join(out, res)
        if out is None:
            joined = pred
            for v in operands:
                joined = joined.join(_scalar(v))
            return joined
        return _join(out, pred if pred.axes else UNIFORM)

    def apply_switch(self, call: ast.Call):
        pred = _scalar(self.eval(call.args[0]))
        divergent = self.divergent
        if pred.axes and not pred.unknown:
            divergent = (call.lineno, "switch index varies per shard")
        out = UNIFORM
        branches = call.args[1]
        fns = []
        if isinstance(branches, (ast.Tuple, ast.List)):
            fns = [self._branch_fn(e) for e in branches.elts]
        operands = [self.eval(a) for a in call.args[2:]]
        for fn in fns:
            if fn is not None:
                out = _join(out, self._run_branch_fn(fn, operands, divergent))
        return out

    def apply_scan(self, call: ast.Call):
        body_fn = self._branch_fn(call.args[0])
        carry = self.eval(call.args[1])
        xs = self.eval(call.args[2]) if len(call.args) > 2 else UNIFORM
        if body_fn is None:
            return _join(carry, xs)
        for _ in range(FIXPOINT_ROUNDS):
            nxt = self._run_quiet(body_fn, [carry, xs])
            if isinstance(nxt, TupleVal) and len(nxt.elts) == 2:
                nxt = nxt.elts[0]
            joined = _join(carry, nxt)
            if joined == carry:
                break
            carry = joined
        out = self._run_branch_fn(body_fn, [carry, xs], self.divergent)
        return _join(carry, out)

    def _run_quiet(self, fn, args):
        sub = BodyInterpreter(
            ctx=self.ctx,
            fn=fn,
            arg_vals=args,
            events=self.events,
            local_env=self.local_env,
            depth=self.depth + 1,
            record=False,
            divergent=None,
            seen=self.seen | {(self.ctx.module.path, fn.name)},
            closure_env=self.env,
            closure_defs=self._local_defs,
        )
        sub.run(fn.body)
        return sub.return_value(args)

    # -- statements ---------------------------------------------------------
    def assign(self, target: ast.AST, value):
        if isinstance(target, ast.Name):
            self.env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, TupleVal) and len(value.elts) == len(target.elts):
                for elt, v in zip(target.elts, value.elts):
                    self.assign(
                        elt.value if isinstance(elt, ast.Starred) else elt, v
                    )
            else:
                collapsed = _scalar(value)
                for elt in target.elts:
                    self.assign(
                        elt.value if isinstance(elt, ast.Starred) else elt,
                        collapsed,
                    )

    def run(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.run_statement(stmt)

    def run_statement(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.FunctionDef):
            return  # interpreted on demand at call sites
        if isinstance(stmt, (ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, ast.Return):
            self.returns.append((self.eval(stmt.value), stmt.lineno))
            return
        if isinstance(stmt, ast.Assign):
            value = self.eval(stmt.value)
            for target in stmt.targets:
                self.assign(target, value)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.assign(stmt.target, self.eval(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            value = _scalar(self.eval(stmt.value))
            if isinstance(stmt.target, ast.Name):
                prev = _scalar(self.env.get(stmt.target.id, UNIFORM))
                self.env[stmt.target.id] = prev.join(value)
            return
        if isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            test = _scalar(self.eval(stmt.test))
            prev = self.divergent
            if test.axes and not test.unknown:
                self.divergent = (
                    stmt.lineno,
                    "branch condition varies per shard",
                )
            self.run(stmt.body)
            self.run(stmt.orelse)
            self.divergent = prev
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            iter_val = _scalar(self.eval(stmt.iter))
            self.assign(stmt.target, iter_val)
            prev = self.divergent
            if iter_val.axes and not iter_val.unknown:
                self.divergent = (stmt.lineno, "loop iterates per-shard data")
            self.run(stmt.body)
            self.run(stmt.orelse)
            self.divergent = prev
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                val = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, val)
            self.run(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.run(stmt.body)
            for handler in stmt.handlers:
                self.run(handler.body)
            self.run(stmt.orelse)
            self.run(stmt.finalbody)
            return


# ---------------------------------------------------------------------------
# shard_map site discovery + module-level scans
# ---------------------------------------------------------------------------

@dataclass
class ShardMapSite:
    path: str
    line: int
    fn: Optional[ast.FunctionDef]
    in_specs: object
    out_specs: object
    local_env: Dict[str, ast.AST]


def _is_shard_map_call(name: Optional[str]) -> Optional[str]:
    if name is None:
        return None
    base = name.split(".")[-1]
    if base in ("shard_map_over", "shard_map"):
        return base
    return None


def _assignment_env(scopes: List[ast.AST]) -> Dict[str, ast.AST]:
    """name -> value-expression for simple assignments in the enclosing
    scopes (outermost first, so inner scopes shadow)."""
    env: Dict[str, ast.AST] = {}
    for scope in scopes:
        body = scope.body if hasattr(scope, "body") else []
        for stmt in body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
            ):
                env[stmt.targets[0].id] = stmt.value
    return env


def _find_def(scopes: List[ast.AST], name: str) -> Optional[ast.FunctionDef]:
    for scope in reversed(scopes):  # innermost first
        for stmt in getattr(scope, "body", []):
            if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                return stmt
    return None


def _unwrap_vmap_expr(expr: Optional[ast.AST]) -> Optional[ast.AST]:
    """Peel `jax.vmap(f)` / `vmap(vmap(f))` wrappers off a function
    expression: vmap adds a batch axis but the wrapped body is still the
    shard_map body whose reductions the specs must match (the fleet
    kernels shard_map vmapped member programs)."""
    while (
        isinstance(expr, ast.Call)
        and (dotted_name(expr.func) or "").split(".")[-1] == "vmap"
        and expr.args
    ):
        expr = expr.args[0]
    return expr


def find_shard_map_sites(ctx: ModuleContext) -> List[ShardMapSite]:
    module = ctx.module
    sites: List[ShardMapSite] = []
    if module.tree is None:
        return sites

    def visit(node: ast.AST, scopes: List[ast.AST]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes = scopes + [node]
        for child in ast.iter_child_nodes(node):
            visit(child, scopes)
        if not isinstance(node, ast.Call):
            return
        base = _is_shard_map_call(dotted_name(node.func))
        if base is None:
            return
        env = _assignment_env(scopes)
        fn_expr = None
        in_expr = None
        out_expr = None
        if base == "shard_map_over":
            if len(node.args) >= 3:
                in_expr, out_expr = node.args[1], node.args[2]
            for kw in node.keywords:
                if kw.arg == "fn":
                    fn_expr = kw.value
                elif kw.arg == "in_specs":
                    in_expr = kw.value
                elif kw.arg == "out_specs":
                    out_expr = kw.value
        else:  # jax.shard_map(f, mesh=..., in_specs=..., out_specs=...)
            if node.args:
                fn_expr = node.args[0]
            for kw in node.keywords:
                if kw.arg == "in_specs":
                    in_expr = kw.value
                elif kw.arg == "out_specs":
                    out_expr = kw.value
        fn_def = None
        fn_expr = _unwrap_vmap_expr(fn_expr)
        if isinstance(fn_expr, ast.Name):
            fn_def = _find_def(scopes, fn_expr.id)
        if fn_def is None:
            return  # decorator form / pass-through param: nothing to walk
        in_specs = (
            parse_spec_expr(ctx, in_expr, env) if in_expr is not None else UNKNOWN
        )
        out_specs = (
            parse_spec_expr(ctx, out_expr, env) if out_expr is not None else UNKNOWN
        )
        sites.append(
            ShardMapSite(
                path=module.path,
                line=node.lineno,
                fn=fn_def,
                in_specs=in_specs,
                out_specs=out_specs,
                local_env=env,
            )
        )

    visit(module.tree, [module.tree])
    return sites


def _scan_axis_literals(ctx: ModuleContext, events: List[SpmdEvent]) -> None:
    """Module-wide axis hygiene, independent of shard_map bodies: every
    collective call's axis argument, every ``P(...)`` entry, and every
    ``create_mesh``/``Mesh`` axis tuple."""
    module = ctx.module
    if module.tree is None or module.path == MESH_PATH:
        return  # mesh.py DECLARES the constants; its literals are the truth

    def check(resolved, line_fallback: int):
        if resolved is None:
            return
        kind, value = resolved[0], resolved[1]
        line = resolved[2] if kind == "literal" else line_fallback
        if value not in ctx.axes.known_axes:
            events.append(
                SpmdEvent(
                    path=module.path, line=line, kind="unknown-axis", detail=value
                )
            )
        elif kind == "literal":
            events.append(
                SpmdEvent(
                    path=module.path,
                    line=line,
                    kind="axis-bypass",
                    detail=value,
                    extra=(ctx.axes.by_value.get(value, ""),),
                )
            )

    # enclosing-scope assignment envs, rebuilt per top-level walk for
    # one-deep Name resolution
    def visit(node: ast.AST, scopes: List[ast.AST]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes = scopes + [node]
        for child in ast.iter_child_nodes(node):
            visit(child, scopes)
        if not isinstance(node, ast.Call):
            return
        env = _assignment_env(scopes)
        name = dotted_name(node.func)
        base = name.split(".")[-1] if name else ""
        collective = ctx.collective_for(node)
        if collective is not None:
            _, _, axis_param, _, _ = collective
            axis_node = None
            if axis_param < len(node.args):
                axis_node = node.args[axis_param]
            else:
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis"):
                        axis_node = kw.value
                        break
            if axis_node is not None:
                check(ctx.resolve_axis(axis_node, env), node.lineno)
            return
        if _is_partition_spec_call(node):
            for arg in node.args:
                if isinstance(arg, ast.Constant) and arg.value is None:
                    continue
                if isinstance(arg, (ast.Tuple, ast.List)):
                    for elt in arg.elts:
                        check(ctx.resolve_axis(elt, env), node.lineno)
                else:
                    check(ctx.resolve_axis(arg, env), node.lineno)
            return
        if base in ("create_mesh", "Mesh"):
            candidates = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg == "axis_names"
            ]
            for cand in candidates:
                if isinstance(cand, (ast.Tuple, ast.List)):
                    for elt in cand.elts:
                        check(ctx.resolve_axis(elt, env), node.lineno)

    visit(module.tree, [module.tree])


def _scan_order_folds(ctx: ModuleContext, events: List[SpmdEvent]) -> None:
    """Manual accumulation of permuted shards outside the sanctioned
    ring fold: a python loop whose body both calls a permute collective
    and accumulates into a loop-carried name reassociates the reduction
    — replica-order bit-exactness lives only in collectives.py/
    overlap.py."""
    module = ctx.module
    if module.tree is None or module.path in SANCTIONED_FOLD_PATHS:
        return

    def loop_has_permute(loop: ast.AST) -> Optional[int]:
        for sub in ast.walk(loop):
            if isinstance(sub, ast.Call):
                collective = ctx.collective_for(sub)
                if collective is not None and collective[1] == "permute":
                    return sub.lineno
        return None

    def loop_accumulates(loop: ast.AST) -> bool:
        for sub in ast.walk(loop):
            if isinstance(sub, ast.AugAssign) and isinstance(
                sub.op, (ast.Add, ast.Sub)
            ):
                return True
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)
                and isinstance(sub.value, ast.BinOp)
                and isinstance(sub.value.op, (ast.Add, ast.Sub))
            ):
                target = sub.targets[0].id
                for operand in ast.walk(sub.value):
                    if isinstance(operand, ast.Name) and operand.id == target:
                        return True
        return False

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.For, ast.While)):
            permute_line = loop_has_permute(node)
            if permute_line is not None and loop_accumulates(node):
                events.append(
                    SpmdEvent(
                        path=module.path,
                        line=permute_line,
                        kind="order-fold",
                        detail="ppermute",
                        extra=(node.lineno,),
                    )
                )


# ---------------------------------------------------------------------------
# the interpretation (project-level, memoized)
# ---------------------------------------------------------------------------

@dataclass
class SpmdInterpretation:
    events: List[SpmdEvent] = field(default_factory=list)
    sites: List[ShardMapSite] = field(default_factory=list)

    def of_kind(self, *kinds: str) -> List[SpmdEvent]:
        wanted = set(kinds)
        return [e for e in self.events if e.kind in wanted]


def _interpret_site(ctx: ModuleContext, site: ShardMapSite, events: List[SpmdEvent]):
    fn = site.fn
    params = [a.arg for a in fn.args.args]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    in_specs = site.in_specs
    if isinstance(in_specs, tuple) and in_specs and in_specs[0] == "spec":
        in_specs = (in_specs,)  # single spec for a single param
    if isinstance(in_specs, tuple) and in_specs and in_specs[0] != "spec":
        if len(in_specs) != len(params):
            events.append(
                SpmdEvent(
                    path=site.path,
                    line=site.line,
                    kind="spec-arity",
                    detail=fn.name,
                    extra=(len(in_specs), len(params)),
                )
            )
        arg_vals = [
            spec_to_absval(in_specs[i]) if i < len(in_specs) else UNKNOWN_VAL
            for i in range(len(params))
        ]
    elif in_specs is UNKNOWN:
        arg_vals = [UNKNOWN_VAL] * len(params)
    else:
        arg_vals = [spec_to_absval(in_specs)] + [UNKNOWN_VAL] * (len(params) - 1)

    interp = BodyInterpreter(
        ctx=ctx,
        fn=fn,
        arg_vals=arg_vals,
        events=events,
        local_env=site.local_env,
    )
    interp.run(fn.body)

    # out_specs vs what actually came back
    out_specs = site.out_specs
    if out_specs is UNKNOWN or not interp.returns:
        return
    for ret_val, ret_line in interp.returns:
        _check_output(site, fn, out_specs, ret_val, ret_line, events)


def _check_output(site, fn, out_specs, ret_val, ret_line, events):
    def check_one(spec, value):
        axes = spec_axes(spec)
        if axes is None:
            return
        v = _scalar(value) if not isinstance(value, TupleVal) else value.collapse()
        if v.unknown:
            return
        leftover = v.axes - axes
        if leftover:
            events.append(
                SpmdEvent(
                    path=site.path,
                    line=ret_line,
                    kind="unreduced-output",
                    detail=fn.name,
                    extra=(tuple(sorted(leftover)), site.line),
                )
            )

    if isinstance(out_specs, tuple) and out_specs and out_specs[0] == "spec":
        check_one(out_specs, ret_val)
    elif isinstance(out_specs, tuple):
        if isinstance(ret_val, TupleVal) and len(ret_val.elts) == len(out_specs):
            for spec, value in zip(out_specs, ret_val.elts):
                check_one(spec, value)
        else:
            for spec in out_specs:
                check_one(spec, ret_val)


def _build_interpretation(project) -> SpmdInterpretation:
    out = SpmdInterpretation()
    for module in project.modules:
        if module.tree is None:
            continue
        ctx = ModuleContext(project, module)
        _scan_axis_literals(ctx, out.events)
        _scan_order_folds(ctx, out.events)
        for site in find_shard_map_sites(ctx):
            out.sites.append(site)
            _interpret_site(ctx, site, out.events)
    # one event per (path, line, kind, detail): branch fns re-interpreted
    # under several contexts would otherwise repeat themselves
    seen: Set[Tuple] = set()
    unique: List[SpmdEvent] = []
    for e in out.events:
        key = (e.path, e.line, e.kind, e.detail)
        if key not in seen:
            seen.add(key)
            unique.append(e)
    out.events = unique
    return out


def interpretation(project) -> SpmdInterpretation:
    return project.index("spmd", _build_interpretation)
