"""Project-wide call graph + interprocedural taint summaries.

tpulint v1 tracked host-sync taint **per function**: a device→host pull
laundered through one helper escaped the pass entirely, because an
unknown call cleared taint (docs/static_analysis.md admitted as much).
This module closes that hole without giving up the under-approximation
discipline — *known* calls are resolved and summarized, *unknown* calls
still launder:

- **Call resolution** (`CallGraph.resolve`): module-qualified, built on
  the same alias machinery as `rules/_jitindex.py`. Resolves module-level
  functions by local name, one-hop ``from``-imports (``from ..ops import
  stats`` → ``stats.fn``), and ``self.``/``cls.`` method calls within the
  defining class. Anything else stays unknown.
- **Summaries** (`CallGraph.summary`): one bounded-depth, memoized,
  cycle-safe :class:`Summary` per function, stating how the function
  behaves *as a function of its parameters*:

  - ``returns_device`` — its return value is device-tainted regardless
    of arguments (it calls into jnp/lax/jitted kernels and returns that);
  - ``returns_params`` — parameter indices whose taint flows through to
    the return value (the function *launders* rather than syncs);
  - ``param_syncs`` — parameters that reach a blocking host sync inside
    the function (``np.asarray``/casts), each with the sink's file:line
    and the qualname chain down to it;
  - ``param_donates`` — parameters passed into a donated position of a
    donating jit kernel (so a *wrapper* around a donating kernel donates
    its own argument's buffer, transitively);
  - ``param_closes`` — parameters (channels) the function closes or
    cancels (the channel-protocol rule's escape analysis).

- **The taint walker** (`TaintWalker`): the linear per-function pass,
  generalized from v1's boolean taint to *source sets* — a value's
  sources are any of ``DEVICE`` and parameter indices — so one walk per
  function yields both the local findings (device-sourced sinks) and the
  summary (param-sourced sinks, return flow). Recursion is cut by an
  in-progress sentinel (a cycle contributes the empty summary —
  conservative, never wrong), and lifted chains are capped at
  ``MAX_CHAIN`` hops.

`host-sync-leak` and `donation-after-use` consult these summaries so the
``np.asarray`` buried two helpers deep is flagged at the top-level call
site with the full call chain in the finding; `channel-protocol` uses
``param_closes`` and `lock-order` reuses `resolve` for its own
acquisition summaries.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .source import SourceModule, dotted_name

#: source token for "a device array" (parameter sources are int indices)
DEVICE = "device"

#: rule id whose suppressions stop a sink from entering callee summaries:
#: a host-sync-leak disable comment on the sink line means the sync is a
#: documented deliberate one, so callers inherit no finding (the annotated
#: helper itself still shows in the --show-suppressed census)
HOST_SYNC_RULE = "host-sync-leak"

#: lifted call chains stop growing past this many hops (bounded depth)
MAX_CHAIN = 8

# attribute reads that return host metadata, not device payloads
META_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "sharding", "itemsize"}

# call targets that return HOST values (clear taint)
HOST_SINKS = {
    "packed_device_get",
    "device_get",  # jax.device_get
    "float",
    "int",
    "bool",
    "len",
    "str",
    "repr",
}


# ---------------------------------------------------------------------------
# declarations and summaries
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FunctionDecl:
    """One statically-declared function: module-level ``def`` or a method
    (qualname ``Class.method``)."""

    path: str  # repo-relative module path
    qualname: str
    params: Tuple[str, ...]  # positional parameter names, in order
    is_method: bool
    node: ast.AST = field(compare=False, hash=False, repr=False)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.path, self.qualname)


@dataclass(frozen=True)
class SyncSite:
    """One host-sync sink a parameter reaches, with the call chain from
    the summarized function down to it (``funcs`` qualnames, outermost
    first; empty = the sink is in the summarized function itself)."""

    kind: str  # "np-pull" | "cast"
    detail: str  # asarray / float / ...
    sink_path: str
    sink_line: int
    funcs: Tuple[str, ...] = ()


@dataclass(frozen=True)
class DonationSite:
    """A parameter's buffer is donated (directly or through wrappers) to
    ``kernel`` at ``sink_path:sink_line``."""

    kernel: str
    sink_path: str
    sink_line: int
    funcs: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Summary:
    """What a function does with its parameters — the unit the
    interprocedural rules consult instead of laundering at the call."""

    returns_device: bool = False
    returns_params: FrozenSet[int] = frozenset()
    param_syncs: Tuple[Tuple[int, Tuple[SyncSite, ...]], ...] = ()
    param_donates: Tuple[Tuple[int, Tuple[DonationSite, ...]], ...] = ()
    param_closes: FrozenSet[int] = frozenset()

    def syncs_for(self, index: int) -> Tuple[SyncSite, ...]:
        for i, sites in self.param_syncs:
            if i == index:
                return sites
        return ()

    def donates_for(self, index: int) -> Tuple[DonationSite, ...]:
        for i, sites in self.param_donates:
            if i == index:
                return sites
        return ()

    @property
    def donated_positions(self) -> Tuple[int, ...]:
        return tuple(sorted(i for i, _ in self.param_donates))


EMPTY_SUMMARY = Summary()


@dataclass
class SyncEvent:
    """One host-sync sink observed while walking a function, with the
    source set of the value it syncs. ``DEVICE`` sources become rule
    findings; parameter sources become summary entries."""

    line: int
    kind: str
    detail: str
    sources: FrozenSet
    sink_path: str
    sink_line: int
    funcs: Tuple[str, ...] = ()  # lifted call chain (empty = direct sink)


@dataclass
class FunctionAnalysis:
    decl: Optional[FunctionDecl]
    events: List[SyncEvent]
    summary: Summary


# ---------------------------------------------------------------------------
# the call graph
# ---------------------------------------------------------------------------

class CallGraph:
    """Declarations, resolution, and memoized per-function analyses over
    one :class:`~.engine.Project`."""

    def __init__(self, project):
        from .rules import _jitindex  # deferred: rules/ imports this module

        self.project = project
        self.jitindex = _jitindex.jit_index(project)
        # path -> {qualname: decl}
        self.by_module: Dict[str, Dict[str, FunctionDecl]] = {}
        # dotted module name -> path
        self.module_paths: Dict[str, str] = {}
        self._analyses: Dict[Tuple[str, str], FunctionAnalysis] = {}
        self._in_progress: Set[Tuple[str, str]] = set()
        for module in project.modules:
            self._declare(module)

    # -- declarations --------------------------------------------------------
    def _declare(self, module: SourceModule) -> None:
        table: Dict[str, FunctionDecl] = {}
        if module.tree is not None:
            for node in module.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    table[node.name] = self._decl(module, node, node.name, False)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            table[f"{node.name}.{item.name}"] = self._decl(
                                module, item, f"{node.name}.{item.name}", True
                            )
        self.by_module[module.path] = table
        if module.module_name:
            self.module_paths[module.module_name] = module.path

    @staticmethod
    def _decl(module, node, qualname, is_method) -> FunctionDecl:
        params = tuple(
            a.arg for a in list(node.args.posonlyargs) + list(node.args.args)
        )
        return FunctionDecl(
            path=module.path,
            qualname=qualname,
            params=params,
            is_method=is_method,
            node=node,
        )

    def decls_in(self, path: str) -> Dict[str, FunctionDecl]:
        return self.by_module.get(path, {})

    # -- resolution ----------------------------------------------------------
    def resolve(
        self,
        module: SourceModule,
        func: ast.AST,
        current_class: Optional[str] = None,
    ) -> Optional[Tuple[FunctionDecl, bool]]:
        """Resolve a call target to its declaration. Returns ``(decl,
        skip_self)`` — ``skip_self`` means the call site's positional args
        start at the decl's second parameter (a bound-method call) — or
        None for anything not statically resolvable."""
        info = self.jitindex.get(module.path)
        table = self.by_module.get(module.path, {})
        if isinstance(func, ast.Name):
            decl = table.get(func.id)
            if decl is not None and not decl.is_method:
                return decl, False
            if info is not None and func.id in info.imports:
                target_module, original = info.imports[func.id]
                target_path = self.module_paths.get(target_module)
                if target_path is not None:
                    decl = self.by_module.get(target_path, {}).get(original)
                    if decl is not None and not decl.is_method:
                        return decl, False
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            root = func.value.id
            if root in ("self", "cls") and current_class:
                decl = table.get(f"{current_class}.{func.attr}")
                if decl is not None:
                    return decl, True
                return None
            # module-alias attribute: `from ..ops import stats; stats.fn(...)`
            if info is not None and root in info.imports:
                target_module, original = info.imports[root]
                target_path = self.module_paths.get(f"{target_module}.{original}")
                if target_path is not None:
                    decl = self.by_module.get(target_path, {}).get(func.attr)
                    if decl is not None and not decl.is_method:
                        return decl, False
        return None

    # -- analysis ------------------------------------------------------------
    def analyze(self, decl: FunctionDecl) -> FunctionAnalysis:
        """Walk ``decl`` once, yielding its local sync events AND its
        summary. Memoized; recursion (a call cycle) sees the empty
        summary — conservative and terminating. With a prepared
        :class:`~.cache.SummaryCache` on the project, servable modules'
        analyses deserialize instead of re-walking (the incremental-lint
        fast path; finding-parity pinned by tests/test_tpulint.py)."""
        cached = self._analyses.get(decl.key)
        if cached is not None:
            return cached
        summary_cache = getattr(self.project, "summary_cache", None)
        if summary_cache is not None:
            entry = summary_cache.lookup(decl.path, decl.qualname)
            if entry is not None:
                events, summary = entry
                analysis = FunctionAnalysis(decl, events, summary)
                self._analyses[decl.key] = analysis
                return analysis
        if decl.key in self._in_progress:
            return FunctionAnalysis(decl, [], EMPTY_SUMMARY)
        self._in_progress.add(decl.key)
        try:
            module = self.project.module_at(decl.path)
            info = self.jitindex.get(decl.path)
            params = list(decl.params)
            current_class = None
            if decl.is_method:
                current_class = decl.qualname.split(".")[0]
                if params and params[0] in ("self", "cls"):
                    params = params[1:]
            walker = TaintWalker(
                graph=self,
                module=module,
                info=info,
                params={name: i for i, name in enumerate(params)},
                current_class=current_class,
            )
            walker.run_block(decl.node.body)
            analysis = FunctionAnalysis(
                decl=decl, events=walker.events, summary=walker.build_summary()
            )
        finally:
            self._in_progress.discard(decl.key)
        self._analyses[decl.key] = analysis
        return analysis

    def summary(self, decl: FunctionDecl) -> Summary:
        return self.analyze(decl).summary

    def donating_functions(
        self, module: SourceModule
    ) -> Dict[str, Tuple[Tuple[int, ...], str]]:
        """Local names in ``module`` that resolve to functions whose
        summaries donate parameters: name -> (positions, chain label).
        The donation-after-use rule merges these with the direct
        jit-kernel donation table."""
        out: Dict[str, Tuple[Tuple[int, ...], str]] = {}
        info = self.jitindex.get(module.path)
        candidates: Dict[str, FunctionDecl] = {}
        for qualname, decl in self.by_module.get(module.path, {}).items():
            if not decl.is_method:
                candidates[qualname] = decl
        if info is not None:
            for bound, (target_module, original) in info.imports.items():
                target_path = self.module_paths.get(target_module)
                if target_path is None:
                    continue
                decl = self.by_module.get(target_path, {}).get(original)
                if decl is not None and not decl.is_method:
                    candidates.setdefault(bound, decl)
        for name, decl in candidates.items():
            summary = self.summary(decl)
            positions = summary.donated_positions
            if not positions:
                continue
            site = summary.donates_for(positions[0])[0]
            label = " -> ".join((decl.qualname,) + site.funcs + (site.kernel,))
            out[name] = (positions, label)
        return out


def get(project) -> CallGraph:
    """The project's memoized call graph (shared across rules)."""
    return project.index("callgraph", CallGraph)


# ---------------------------------------------------------------------------
# the source-set taint walker
# ---------------------------------------------------------------------------

class TaintWalker:
    """Linear taint pass over one function body (or the module level),
    tracking *source sets* per name: ``DEVICE`` and/or parameter indices.

    With ``graph=None`` the walker degrades to tpulint v1's per-function
    behavior — every call is unknown and launders — which the tier-1
    superset test uses as the recall baseline.
    """

    def __init__(
        self,
        graph: Optional[CallGraph],
        module: SourceModule,
        info,
        params: Optional[Dict[str, int]] = None,
        current_class: Optional[str] = None,
    ):
        self.graph = graph
        self.module = module
        self.info = info
        self.current_class = current_class
        self.env: Dict[str, FrozenSet] = {
            name: frozenset({index}) for name, index in (params or {}).items()
        }
        self.events: List[SyncEvent] = []
        self.returns: Set = set()
        self._param_syncs: Dict[int, List[SyncSite]] = {}
        self._param_donates: Dict[int, List[DonationSite]] = {}
        self._param_closes: Set[int] = set()

    # -- summary assembly ----------------------------------------------------
    def build_summary(self) -> Summary:
        # a suppression on the sink line documents the sync as deliberate:
        # the site stays out of the summary, so callers inherit no finding
        # (lifted sites were filtered when the deeper summary was built)
        suppressed_sinks = set(self.module.suppressions_for(HOST_SYNC_RULE))
        # parameter-sourced sink events fold into the summary
        for event in self.events:
            for source in event.sources:
                if source == DEVICE:
                    continue
                if event.kind in ("np-pull", "cast"):
                    if not event.funcs and event.sink_line in suppressed_sinks:
                        continue
                    self._param_syncs.setdefault(source, []).append(
                        SyncSite(
                            kind=event.kind,
                            detail=event.detail,
                            sink_path=event.sink_path,
                            sink_line=event.sink_line,
                            funcs=event.funcs,
                        )
                    )
        return Summary(
            returns_device=DEVICE in self.returns,
            returns_params=frozenset(s for s in self.returns if s != DEVICE),
            param_syncs=tuple(
                (i, tuple(sites)) for i, sites in sorted(self._param_syncs.items())
            ),
            param_donates=tuple(
                (i, tuple(sites)) for i, sites in sorted(self._param_donates.items())
            ),
            param_closes=frozenset(self._param_closes),
        )

    # -- source evaluation ---------------------------------------------------
    def sources(self, node: ast.AST) -> FrozenSet:
        if isinstance(node, ast.Name):
            return self.env.get(node.id, frozenset())
        if isinstance(node, ast.Call):
            return self.call_sources(node)
        if isinstance(node, ast.Attribute):
            if node.attr in META_ATTRS:
                return frozenset()
            return self.sources(node.value)
        if isinstance(node, ast.Subscript):
            return self.sources(node.value)
        if isinstance(node, ast.BinOp):
            return self.sources(node.left) | self.sources(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.sources(node.operand)
        if isinstance(node, ast.IfExp):
            return self.sources(node.body) | self.sources(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            out: FrozenSet = frozenset()
            for elt in node.elts:
                out |= self.sources(elt)
            return out
        if isinstance(node, ast.Starred):
            return self.sources(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.sources(node.value)
        return frozenset()

    def _arg_sources(self, call: ast.Call, index: int, decl, skip_self) -> FrozenSet:
        """Sources of the value bound to the callee's parameter ``index``
        (indices count AFTER self for method calls)."""
        args = call.args
        if index < len(args):
            arg = args[index]
            if isinstance(arg, ast.Starred):
                return frozenset()
            return self.sources(arg)
        params = list(decl.params)
        if skip_self and params and params[0] in ("self", "cls"):
            params = params[1:]
        if index < len(params):
            name = params[index]
            for kw in call.keywords:
                if kw.arg == name:
                    return self.sources(kw.value)
        return frozenset()

    def call_sources(self, call: ast.Call) -> FrozenSet:
        func = call.func
        name = dotted_name(func)
        if name is not None:
            base = name.split(".")[-1]
            if base in HOST_SINKS:
                return frozenset()
            root = name.split(".")[0]
            if root in self.info.np_aliases:
                return frozenset()  # numpy returns host arrays
            if self.info.device_namespace_call(func):
                return frozenset({DEVICE})
            if name in self.info.kernels:
                return frozenset({DEVICE})
            if base == "device_constants":
                return frozenset({DEVICE})
        # keyed factory double-call: jit_find_closest(measure)(X, C)
        if isinstance(func, ast.Call):
            inner = dotted_name(func.func)
            if inner is not None and (
                inner in self.info.factories or inner in self.info.keyed_jit_names
            ):
                return frozenset({DEVICE})
            if self.info.is_jit_callable(func.func):
                return frozenset({DEVICE})  # jax.jit(f)(args) / lazy_jit(f)(args)
        # known callee: taint flows per the summary instead of laundering
        resolved = self._resolve(call)
        if resolved is not None:
            decl, skip_self = resolved
            summary = self.graph.summary(decl)
            out: Set = set()
            if summary.returns_device:
                out.add(DEVICE)
            for index in summary.returns_params:
                out |= self._arg_sources(call, index, decl, skip_self)
            return frozenset(out)
        # x.method() where x carries sources: device-array methods stay on
        # device; a param's method result keeps the param's sources
        if (
            isinstance(func, ast.Attribute)
            and func.attr not in META_ATTRS
            and self.sources(func.value)
        ):
            return self.sources(func.value)
        return frozenset()

    def _resolve(self, call: ast.Call):
        if self.graph is None:
            return None
        func = call.func
        name = dotted_name(func)
        # jitted kernels/factories are device producers, not summarizable
        # host code (their bodies run at trace time)
        if name is not None and (
            name in self.info.kernels or name in self.info.factories
        ):
            return None
        return self.graph.resolve(self.module, func, self.current_class)

    # -- statement handling --------------------------------------------------
    def assign(self, target: ast.AST, value_sources: FrozenSet) -> None:
        if isinstance(target, ast.Name):
            if value_sources:
                self.env[target.id] = value_sources
            else:
                self.env.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign(
                    elt.value if isinstance(elt, ast.Starred) else elt,
                    value_sources,
                )

    def run_block(self, body) -> None:
        for stmt in body:
            self.run_statement(stmt)

    def run_statement(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope, analyzed on its own
        self.scan_expressions(stmt)
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.returns |= self.sources(stmt.value)
        elif isinstance(stmt, ast.Assign):
            value_sources = self.sources(stmt.value)
            for target in stmt.targets:
                self.assign(target, value_sources)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.assign(stmt.target, self.sources(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                merged = self.sources(stmt.value) | self.sources(stmt.target)
                if merged:
                    self.env[stmt.target.id] = merged
        elif isinstance(stmt, ast.For):
            self.assign(stmt.target, self.sources(stmt.iter))
            self.run_block(stmt.body)
            self.run_block(stmt.orelse)
            return
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, self.sources(item.context_expr))
            self.run_block(stmt.body)
            return
        for block in (
            getattr(stmt, "body", None),
            getattr(stmt, "orelse", None),
            getattr(stmt, "finalbody", None),
        ):
            if block and isinstance(block, list):
                self.run_block(block)
        for handler in getattr(stmt, "handlers", []) or []:
            self.run_block(handler.body)

    # -- sink detection ------------------------------------------------------
    def scan_expressions(self, stmt: ast.stmt) -> None:
        from .rules import _astwalk

        for header in _astwalk.header_nodes(stmt):
            for node in ast.walk(header):
                if isinstance(node, ast.Call):
                    self.check_call(node)

    def _emit(
        self,
        line: int,
        kind: str,
        detail: str,
        sources: FrozenSet,
        sink_path: Optional[str] = None,
        sink_line: Optional[int] = None,
        funcs: Tuple[str, ...] = (),
    ) -> None:
        self.events.append(
            SyncEvent(
                line=line,
                kind=kind,
                detail=detail,
                sources=sources,
                sink_path=sink_path if sink_path is not None else self.module.path,
                sink_line=sink_line if sink_line is not None else line,
                funcs=funcs,
            )
        )

    def check_call(self, call: ast.Call) -> None:
        func = call.func
        name = dotted_name(func)

        # block_until_ready: barrier outside the accounted funnels —
        # unconditionally a local finding, never lifted (the helper's own
        # module already reports it)
        if (isinstance(func, ast.Attribute) and func.attr == "block_until_ready") or (
            name is not None and name.split(".")[-1] == "block_until_ready"
        ):
            self._emit(call.lineno, "barrier", "block_until_ready", frozenset({DEVICE}))
            return

        # .item(): always a scalar pull, always local
        if isinstance(func, ast.Attribute) and func.attr == "item" and not call.args:
            self._emit(call.lineno, "item", "item", frozenset({DEVICE}))
            return

        # channel close/cancel on a parameter (channel-protocol summary)
        if isinstance(func, ast.Attribute) and func.attr in ("close", "cancel"):
            for source in self.sources(func.value):
                if source != DEVICE:
                    self._param_closes.add(source)

        if name is not None and call.args:
            root, _, rest = name.partition(".")
            arg = call.args[0]
            # np.asarray / np.array on a sourced value
            if root in self.info.np_aliases and rest in (
                "asarray",
                "array",
                "ascontiguousarray",
            ):
                arg_sources = self.sources(arg)
                if arg_sources:
                    self._emit(call.lineno, "np-pull", rest, arg_sources)
            # float()/int()/bool() casts on a sourced value
            elif name in ("float", "int", "bool"):
                arg_sources = self.sources(arg)
                if arg_sources:
                    self._emit(call.lineno, "cast", name, arg_sources)

        # direct donation: donating kernel called with a param-sourced name
        if name is not None and name in self.info.kernels:
            positions = self.info.kernels[name]
            if positions and not any(
                isinstance(a, ast.Starred) for a in call.args
            ):
                for pos in positions:
                    if pos < len(call.args) and isinstance(call.args[pos], ast.Name):
                        for source in self.env.get(call.args[pos].id, frozenset()):
                            if source != DEVICE:
                                self._param_donates.setdefault(source, []).append(
                                    DonationSite(
                                        kernel=name,
                                        sink_path=self.module.path,
                                        sink_line=call.lineno,
                                    )
                                )

        # interprocedural lifting: consult the callee's summary
        resolved = self._resolve(call)
        if resolved is None:
            return
        decl, skip_self = resolved
        summary = self.graph.summary(decl)
        for index, sites in summary.param_syncs:
            arg_sources = self._arg_sources(call, index, decl, skip_self)
            if not arg_sources:
                continue
            for site in sites:
                if len(site.funcs) >= MAX_CHAIN:
                    continue  # bounded-depth: stop lifting runaway chains
                self._emit(
                    call.lineno,
                    site.kind,
                    site.detail,
                    arg_sources,
                    sink_path=site.sink_path,
                    sink_line=site.sink_line,
                    funcs=(decl.qualname,) + site.funcs,
                )
        for index, sites in summary.param_donates:
            arg_sources = self._arg_sources(call, index, decl, skip_self)
            for source in arg_sources:
                if source == DEVICE:
                    continue
                for site in sites:
                    if len(site.funcs) >= MAX_CHAIN:
                        continue
                    self._param_donates.setdefault(source, []).append(
                        DonationSite(
                            kernel=site.kernel,
                            sink_path=site.sink_path,
                            sink_line=site.sink_line,
                            funcs=(decl.qualname,) + site.funcs,
                        )
                    )
        for index in summary.param_closes:
            arg_sources = self._arg_sources(call, index, decl, skip_self)
            for source in arg_sources:
                if source != DEVICE:
                    self._param_closes.add(source)
