"""Shared source model for tpulint rules.

One ``SourceModule`` per scanned file carries everything a rule may need —
the raw text, the comment/string-stripped text (the ``_code_only``
transform that previously lived as four identical copies across the
``scripts/check_*.py`` gates), the parsed AST, and the file's
``# tpulint: disable=`` suppressions — so every rule reads the file once
and reports line numbers against the same coordinates.

Suppression syntax::

    x = device_value.item()  # tpulint: disable=host-sync-leak -- drain point

    # tpulint: disable=retrace-hazard -- per-plan cache keyed on stage ids
    self._jit = jax.jit(self._run)

A suppression on its own line covers the next source line; an inline
suppression covers its own line. Several ids separate with commas. The
``-- reason`` tail is the etiquette half of the contract: a suppression
turns a finding into documentation, and documentation without a WHY is
noise (docs/static_analysis.md). Suppressions that match no finding are
themselves reported (rule id ``unused-suppression``) so stale annotations
cannot rot in place.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,-]+)(?:\s*--\s*(.*\S))?\s*$"
)


@dataclass
class Suppression:
    """One ``# tpulint: disable=<rule>`` comment, resolved to the source
    line it covers."""

    rule: str
    line: int  # line the suppression COVERS (not necessarily the comment's)
    comment_line: int
    reason: str = ""
    used: bool = False


def code_only(source: str) -> str:
    """``source`` with comments and string/docstring tokens blanked
    (newlines kept, so reported line numbers stay true).

    This is THE shared copy of the helper the four legacy gate scripts
    each carried privately; they now import it from here.
    """
    out = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return source
    lines = source.splitlines(keepends=True)
    drop = []  # (srow, scol, erow, ecol) spans to blank
    for tok in tokens:
        if tok.type in (tokenize.COMMENT, tokenize.STRING):
            drop.append((tok.start, tok.end))
    for line_no, line in enumerate(lines, start=1):
        buf = list(line)
        for (srow, scol), (erow, ecol) in drop:
            if srow <= line_no <= erow:
                lo = scol if line_no == srow else 0
                hi = ecol if line_no == erow else len(buf)
                for i in range(lo, min(hi, len(buf))):
                    if buf[i] not in "\r\n":
                        buf[i] = " "
        out.append("".join(buf))
    return "".join(out)


def _parse_suppressions(source: str) -> List[Suppression]:
    """Extract suppressions via the tokenizer (a ``# tpulint:`` inside a
    string literal is not a suppression)."""
    suppressions: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return suppressions
    lines = source.splitlines()
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        match = _SUPPRESS_RE.search(tok.string)
        if not match:
            continue
        comment_line = tok.start[0]
        text_before = lines[comment_line - 1][: tok.start[1]]
        if text_before.strip():
            covered = comment_line  # inline: covers its own line
        else:
            # standalone comment: covers the next non-blank, non-comment line
            covered = comment_line
            for lookahead in range(comment_line, len(lines)):
                candidate = lines[lookahead].strip()
                if candidate and not candidate.startswith("#"):
                    covered = lookahead + 1
                    break
        for rule_id in match.group(1).split(","):
            rule_id = rule_id.strip()
            if rule_id:
                suppressions.append(
                    Suppression(
                        rule=rule_id,
                        line=covered,
                        comment_line=comment_line,
                        reason=(match.group(2) or "").strip(),
                    )
                )
    return suppressions


@dataclass
class SourceModule:
    """One parsed source file, shared by every rule that inspects it."""

    path: str  # repo-relative, forward slashes
    abspath: str
    source: str
    stripped: str = ""  # comment/string-blanked source (code_only)
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None
    suppressions: List[Suppression] = field(default_factory=list)
    module_name: str = ""  # dotted import path, e.g. flink_ml_tpu.ops.tokens
    is_package: bool = False  # an __init__.py (relative imports resolve to itself)

    @classmethod
    def load(cls, abspath: str, relpath: str) -> "SourceModule":
        with open(abspath) as f:
            source = f.read()
        mod = cls(path=relpath.replace("\\", "/"), abspath=abspath, source=source)
        mod.stripped = code_only(source)
        mod.suppressions = _parse_suppressions(source)
        parts = mod.path[:-3].split("/") if mod.path.endswith(".py") else []
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
            mod.is_package = True
        mod.module_name = ".".join(parts)
        try:
            mod.tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            mod.parse_error = f"{e.__class__.__name__}: {e}"
        return mod

    def stripped_lines(self) -> List[str]:
        return self.stripped.splitlines()

    def suppressions_for(self, rule_id: str) -> Dict[int, Suppression]:
        return {s.line: s for s in self.suppressions if s.rule == rule_id}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_relative_import(
    module_name: str, node: ast.ImportFrom, is_package: bool = False
) -> Optional[str]:
    """The absolute dotted module an ``ImportFrom`` pulls from, resolving
    leading dots against ``module_name`` (the importing module)."""
    if node.level == 0:
        return node.module
    base = module_name.split(".")
    # one dot reaches the containing package: the module itself when the
    # importer is a package __init__, its parent otherwise
    trim = node.level - 1 if is_package else node.level
    if trim > len(base):
        return None
    prefix = base[: len(base) - trim] if trim else base
    if node.module:
        return ".".join(prefix + [node.module])
    return ".".join(prefix) or None
