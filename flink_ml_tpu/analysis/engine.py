"""tpulint engine: rule registry, project scan, suppression resolution.

A rule is a subclass of :class:`Rule` registered via :func:`register`. It
declares its identity and documentation as class attributes and yields
:class:`Finding` objects from ``check_project`` (project-wide rules) or
``check_module`` (per-file rules, driven once per in-scope file).

The engine:

1. walks ``flink_ml_tpu/`` building one :class:`SourceModule` per file,
2. runs every rule over the modules in its declared ``scope``,
3. drops findings covered by a ``# tpulint: disable=<rule>`` suppression
   on the finding's line (marking the suppression used),
4. reports every *unused* suppression as a finding of the built-in
   ``unused-suppression`` rule — a stale annotation is a lie about the
   code and rots the audit trail the suppressions exist to provide.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Type

from .source import SourceModule

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_SCOPE = ("flink_ml_tpu",)

UNUSED_SUPPRESSION = "unused-suppression"


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file:line."""

    path: str  # repo-relative
    line: int
    rule: str
    message: str
    data: Tuple = ()  # structured payload for shims/tests (rule-specific)

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


class Rule:
    """Base class for tpulint rules. Subclasses set the metadata attributes
    and override one of the check hooks."""

    id: str = ""
    title: str = ""
    rationale: str = ""  # the WHY, rendered by --list-rules and the docs
    example: str = ""  # a minimal offending snippet
    scope: Tuple[str, ...] = DEFAULT_SCOPE  # repo-relative path prefixes
    exclude: Tuple[str, ...] = ()  # repo-relative path prefixes to skip
    requires_import: bool = False  # imports the package (coverage gates)

    def applies_to(self, path: str) -> bool:
        path = path.replace("\\", "/")
        if not any(
            path == p or path.startswith(p.rstrip("/") + "/") for p in self.scope
        ):
            return False
        return not any(
            path == p or path.startswith(p.rstrip("/") + "/") for p in self.exclude
        )

    def check_project(self, project: "Project") -> Iterable[Finding]:
        for module in project.modules:
            if self.applies_to(module.path):
                yield from self.check_module(project, module)

    def check_module(
        self, project: "Project", module: SourceModule
    ) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule (as a singleton instance) to the
    registry. Rule ids must be unique."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> List[Rule]:
    _load_rules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    _load_rules()
    return _REGISTRY[rule_id]


def _load_rules() -> None:
    from . import rules  # noqa: F401  (imports register every rule module)


@dataclass
class Project:
    """The scanned tree plus lazily-built cross-module indexes."""

    root: str
    modules: List[SourceModule] = field(default_factory=list)
    _by_path: Dict[str, SourceModule] = field(default_factory=dict)
    _by_module_name: Dict[str, SourceModule] = field(default_factory=dict)
    _indexes: Dict[str, Any] = field(default_factory=dict)
    #: prepared analysis.cache.SummaryCache (incremental lint), or None
    summary_cache: Any = None

    @classmethod
    def load(
        cls, root: str = REPO_ROOT, scope: Sequence[str] = DEFAULT_SCOPE
    ) -> "Project":
        project = cls(root=root)
        for prefix in scope:
            base = os.path.join(root, prefix)
            if os.path.isfile(base):
                project.add(SourceModule.load(base, os.path.relpath(base, root)))
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for fname in sorted(filenames):
                    if not fname.endswith(".py"):
                        continue
                    abspath = os.path.join(dirpath, fname)
                    project.add(
                        SourceModule.load(abspath, os.path.relpath(abspath, root))
                    )
        return project

    def add(self, module: SourceModule) -> None:
        if module.path in self._by_path:
            return
        self.modules.append(module)
        self._by_path[module.path] = module
        if module.module_name:
            self._by_module_name[module.module_name] = module

    def module_at(self, path: str) -> Optional[SourceModule]:
        return self._by_path.get(path.replace("\\", "/"))

    def module_named(self, dotted: str) -> Optional[SourceModule]:
        return self._by_module_name.get(dotted)

    def index(self, key: str, build) -> Any:
        """Memoized cross-module index (e.g. the jit-kernel registry the
        host-sync and donation rules share)."""
        if key not in self._indexes:
            self._indexes[key] = build(self)
        return self._indexes[key]


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # unsuppressed
    suppressed: List[Finding] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def run(
    root: str = REPO_ROOT,
    scope: Sequence[str] = DEFAULT_SCOPE,
    rules: Optional[Sequence[Rule]] = None,
    only_paths: Optional[Sequence[str]] = None,
    project: Optional[Project] = None,
    summary_cache: Any = None,
) -> Report:
    """Run ``rules`` (default: all registered) over the tree.

    ``only_paths`` filters *reported* findings to the given repo-relative
    files (the ``--changed`` fast path) — project-wide rules still see the
    whole tree, so cross-file invariants cannot be dodged by a partial
    lint; only the blame anchored elsewhere is dropped.

    ``summary_cache`` (analysis.cache.SummaryCache) serves cached
    call-graph analyses for modules proven clean by content hash (minus
    the reverse-import closure of the dirty set) and is refreshed from
    this run's results afterwards — events are cached alongside
    summaries, so a warm run is finding-identical to a cold one.
    """
    if project is None:
        project = Project.load(root=root, scope=scope)
    if rules is None:
        rules = all_rules()
    if summary_cache is not None:
        summary_cache.prepare(project)
        project.summary_cache = summary_cache

    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check_project(project))

    report = Report()
    for finding in sorted(raw, key=lambda f: (f.path, f.line, f.rule, f.message)):
        module = project.module_at(finding.path)
        suppression = None
        if module is not None:
            suppression = module.suppressions_for(finding.rule).get(finding.line)
        if suppression is not None:
            suppression.used = True
            report.suppressed.append(finding)
        else:
            report.findings.append(finding)

    unused: List[Finding] = []
    known = {r.id for r in all_rules()} | {UNUSED_SUPPRESSION}
    for module in project.modules:
        for s in module.suppressions:
            if s.used:
                continue
            if s.rule not in known:
                message = (
                    f"suppression names unknown rule {s.rule!r} "
                    "(see scripts/tpulint.py --list-rules)"
                )
            else:
                message = (
                    f"unused suppression of {s.rule!r} — no finding on "
                    f"line {s.line}; delete the stale annotation"
                )
            unused.append(
                Finding(
                    path=module.path,
                    line=s.comment_line,
                    rule=UNUSED_SUPPRESSION,
                    message=message,
                )
            )
    report.findings.extend(
        sorted(unused, key=lambda f: (f.path, f.line, f.message))
    )

    if only_paths is not None:
        selected = {p.replace("\\", "/") for p in only_paths}
        report.findings = [f for f in report.findings if f.path in selected]
        report.suppressed = [f for f in report.suppressed if f.path in selected]

    if summary_cache is not None:
        graph = project._indexes.get("callgraph")
        if graph is not None:
            summary_cache.store_analyses(graph)
            summary_cache.save()
    return report
