"""Runtime concurrency sanitizer — the dynamic twin of the static rules.

``FLINK_ML_TPU_SANITIZE=1`` turns the test run into a concurrency
recorder: every ``flow.BoundedChannel`` condition variable (and the obs
tracing lock) is wrapped so acquisitions are observed, every
``flow.pump``/``flow.spawn`` worker is registered, and every channel's
open→close lifecycle is balanced. At process exit (or pytest session
end — see tests/conftest.py) the recorder fails on:

- **lock-order cycles** in the observed cross-thread acquisition DAG —
  the edge A→B is recorded when a thread *attempts* B while holding A
  (attempt-time, so a real deadlock still leaves its evidence), and a
  cycle means two code paths disagree about the global order;
- **leaked workers** — a pump/spawn thread still alive after a bounded
  join: its consumer abandoned it without the close/cancel handshake,
  the silently-stalled-worker state the flow contract exists to kill;
- **unclosed pump channels** — a channel that had a producer worker
  attached but was never closed (by the worker) or cancelled (by the
  consumer);
- **collective-sequence divergence** — the dynamic dual of the static
  ``collective-divergence`` rule (tpulint v3): every accounted
  collective (``parallel/collectives.py`` funnels through
  ``record_collective``) appends its ``(op, axis, shape, dtype)`` to
  the sequence of the current *shard scope* (``Recorder.shard_scope``
  — entered by per-shard host-driven paths and the multi-host
  emulation; default scope = the single trace context), and at exit
  every shard of a scope group must have recorded the SAME sequence.
  A mismatch is the SPMD-divergence deadlock caught in the virtual
  mesh instead of hung on a production DCN fabric.

The static rules (`lock-order`, `channel-protocol`) prove the *code*
cannot express an inversion the analyzer can see; the sanitizer proves
the *executions the tests actually drove* stayed clean — each covers the
other's blind spot (dynamic dispatch the analyzer had to skip; the
interleaving the tests never ran). Both report the same hazard class in
the same vocabulary (docs/static_analysis.md).

Everything here is dependency-free host plumbing: safe to import before
jax, cheap enough to leave on for a whole suite (one dict update per
lock op, under the recorder's own internal lock).
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

ENV_FLAG = "FLINK_ML_TPU_SANITIZE"

__all__ = [
    "SanitizerError",
    "Recorder",
    "recorder",
    "enabled_by_env",
    "enable",
    "tracked_lock",
    "tracked_rlock",
    "tracked_condition",
    "record_collective",
    "collective_recording",
]


class SanitizerError(AssertionError):
    """Raised by :func:`check` when the recorded execution violated the
    concurrency contract (cycle / leaked worker / unclosed channel)."""


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "").strip() not in ("", "0", "false", "off")


class Recorder:
    """The global acquisition-DAG + worker/channel ledger."""

    def __init__(self) -> None:
        self._mu = threading.Lock()  # guards the ledgers; never held while blocking
        self._held = threading.local()  # per-thread stack of lock names
        # (holder, acquired) -> sample: (thread name, count)
        self.edges: Dict[Tuple[str, str], List] = {}
        self.acquisitions = 0
        # id(channel) -> [name, pumped, closed]
        self._channels: Dict[int, List] = {}
        self._workers: List[Tuple[threading.Thread, str]] = []
        # group -> shard -> [(op, axis, shape, dtype), ...]
        self.collective_sequences: Dict[str, Dict[str, List[Tuple]]] = {}
        self.collective_count = 0
        self._shard_ctx = threading.local()  # per-thread (group, shard)

    # -- lock events ---------------------------------------------------------
    def _stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = []
            self._held.stack = stack
        return stack

    def on_attempt(self, name: str) -> None:
        """Record edges BEFORE blocking on the acquire, so a genuine
        deadlock still leaves the inversion in the ledger."""
        stack = self._stack()
        if not stack:
            return
        thread = threading.current_thread().name
        with self._mu:
            for holder in stack:
                if holder == name:
                    continue  # reentrant re-acquire, not an ordering edge
                entry = self.edges.setdefault((holder, name), [thread, 0])
                entry[1] += 1

    def on_acquired(self, name: str) -> None:
        self._stack().append(name)
        with self._mu:
            self.acquisitions += 1

    def on_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- channel / worker ledger ---------------------------------------------
    def register_channel(self, channel) -> None:
        with self._mu:
            self._channels[id(channel)] = [getattr(channel, "name", "channel"), False, False]

    def channel_pumped(self, channel) -> None:
        with self._mu:
            entry = self._channels.get(id(channel))
            if entry is not None:
                entry[1] = True

    def channel_closed(self, channel) -> None:
        with self._mu:
            entry = self._channels.get(id(channel))
            if entry is not None:
                entry[2] = True

    def register_worker(self, thread: threading.Thread, kind: str) -> None:
        with self._mu:
            self._workers.append((thread, kind))

    # -- collective-sequence ledger -------------------------------------------
    def shard_scope(self, shard, group: str = "mesh"):
        """Context manager entering a per-shard recording scope: every
        collective recorded inside appends to ``group``'s sequence for
        ``shard``. Per-shard host-driven paths (and the multi-host
        emulation, one scope per virtual host) wrap their per-shard work
        in this so divergence across shards is observable."""
        rec = self

        class _Scope:
            def __enter__(self_inner):
                prev = getattr(rec._shard_ctx, "scope", None)
                rec._shard_ctx.scope = (str(group), str(shard))
                self_inner._prev = prev
                return rec

            def __exit__(self_inner, *exc):
                rec._shard_ctx.scope = self_inner._prev
                return False

        return _Scope()

    def record_collective(self, op: str, axis, shape, dtype) -> None:
        """One accounted collective: appended to the current shard
        scope's sequence (default scope: the process-wide trace context,
        which cannot diverge against itself)."""
        scope = getattr(self._shard_ctx, "scope", None)
        if scope is None:
            scope = ("trace", "0")
        group, shard = scope
        event = (str(op), str(axis), tuple(shape), str(dtype))
        with self._mu:
            self.collective_sequences.setdefault(group, {}).setdefault(
                shard, []
            ).append(event)
            self.collective_count += 1

    def collective_divergences(self) -> List[str]:
        """Cross-shard sequence mismatches, one message per group."""
        with self._mu:
            groups = {
                g: {s: list(seq) for s, seq in shards.items()}
                for g, shards in self.collective_sequences.items()
            }
        out: List[str] = []
        for group, shards in sorted(groups.items()):
            if len(shards) < 2:
                continue
            names = sorted(shards)
            ref_name, ref = names[0], shards[names[0]]
            for name in names[1:]:
                seq = shards[name]
                limit = min(len(ref), len(seq))
                mismatch = next(
                    (i for i in range(limit) if ref[i] != seq[i]), None
                )
                if mismatch is None and len(ref) == len(seq):
                    continue
                if mismatch is None:
                    longer, shorter = (
                        (ref_name, name) if len(ref) > len(seq) else (name, ref_name)
                    )
                    extra = (ref if len(ref) > len(seq) else seq)[limit]
                    out.append(
                        f"collective-sequence divergence in group {group!r}: "
                        f"shard {longer!r} issued {extra} at position {limit} "
                        f"but shard {shorter!r} ended after {limit} "
                        "collective(s) — the shorter shard would deadlock "
                        "the longer one on a real mesh"
                    )
                else:
                    out.append(
                        f"collective-sequence divergence in group {group!r} "
                        f"at position {mismatch}: shard {ref_name!r} issued "
                        f"{ref[mismatch]} but shard {name!r} issued "
                        f"{seq[mismatch]} — mismatched collectives deadlock "
                        "a multi-host mesh (see the collective-divergence "
                        "lint rule for the static dual)"
                    )
                break  # one message per divergent pair is enough evidence
        return out

    # -- verdicts ------------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the recorded acquisition DAG (one
        representative per cycle, smallest node first)."""
        with self._mu:
            adjacency: Dict[str, Set[str]] = {}
            for holder, acquired in self.edges:
                adjacency.setdefault(holder, set()).add(acquired)
        out: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()

        def dfs(start: str, current: str, path: List[str]) -> None:
            for nxt in sorted(adjacency.get(current, ())):
                if nxt == start:
                    key = tuple(path)
                    if key not in seen:
                        seen.add(key)
                        out.append(list(path))
                elif nxt not in path and nxt > start:
                    dfs(start, nxt, path + [nxt])

        for node in sorted(adjacency):
            dfs(node, node, [node])
        return out

    def problems(self, join_timeout: float = 2.0) -> List[str]:
        """Everything wrong with the recorded execution, as messages."""
        out: List[str] = []
        for cycle in self.cycles():
            order = " -> ".join(cycle + [cycle[0]])
            with self._mu:
                evidence = "; ".join(
                    f"{a}->{b} (thread {self.edges[(a, b)][0]}, x{self.edges[(a, b)][1]})"
                    for a, b in zip(cycle, cycle[1:] + [cycle[0]])
                    if (a, b) in self.edges
                )
            out.append(f"lock-order cycle: {order} [{evidence}]")
        with self._mu:
            workers = list(self._workers)
            channels = list(self._channels.values())
        for thread, kind in workers:
            if thread.is_alive():
                thread.join(join_timeout)
            if thread.is_alive():
                out.append(
                    f"leaked worker: {kind} thread {thread.name!r} still "
                    "alive at exit — its consumer never closed/cancelled "
                    "the handshake channel"
                )
        for name, pumped, closed in channels:
            if pumped and not closed:
                out.append(
                    f"unclosed pump channel {name!r}: a producer worker was "
                    "attached but close()/cancel() never ran"
                )
        out.extend(self.collective_divergences())
        return out

    def check(self, join_timeout: float = 2.0) -> None:
        found = self.problems(join_timeout)
        if found:
            raise SanitizerError(
                "concurrency sanitizer: "
                + "; ".join(found)
                + f" (after {self.acquisitions} recorded acquisitions)"
            )

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {
                "acquisitions": self.acquisitions,
                "edges": len(self.edges),
                "channels": len(self._channels),
                "channelsClosed": sum(1 for c in self._channels.values() if c[2]),
                "workers": len(self._workers),
                "collectives": self.collective_count,
                "collectiveGroups": len(self.collective_sequences),
            }


#: the process-wide recorder (fresh instances are for unit tests)
recorder = Recorder()


# ---------------------------------------------------------------------------
# tracked lock wrappers
# ---------------------------------------------------------------------------

class _TrackedBase:
    """Context-manager + acquire/release shim over a real lock object,
    reporting to a :class:`Recorder`."""

    def __init__(self, name: str, rec: Optional[Recorder] = None, inner=None):
        self._name = name
        self._rec = rec if rec is not None else recorder
        self._inner = inner

    def acquire(self, *args, **kwargs):
        self._rec.on_attempt(self._name)
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._rec.on_acquired(self._name)
        return got

    def release(self):
        self._rec.on_release(self._name)
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()


class TrackedLock(_TrackedBase):
    def __init__(self, name: str, rec: Optional[Recorder] = None, inner=None):
        super().__init__(name, rec, inner if inner is not None else threading.Lock())


class TrackedRLock(_TrackedBase):
    def __init__(self, name: str, rec: Optional[Recorder] = None, inner=None):
        super().__init__(name, rec, inner if inner is not None else threading.RLock())


class TrackedCondition(_TrackedBase):
    """Condition wrapper: the wait() internal release/re-acquire is
    reported too, so the held-stack stays truthful across waits."""

    def __init__(self, name: str, rec: Optional[Recorder] = None, inner=None):
        super().__init__(
            name, rec, inner if inner is not None else threading.Condition()
        )

    def wait(self, timeout: Optional[float] = None):
        self._rec.on_release(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._rec.on_acquired(self._name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._rec.on_release(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._rec.on_acquired(self._name)

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()


def tracked_lock(name: str, rec: Optional[Recorder] = None) -> TrackedLock:
    return TrackedLock(name, rec)


def tracked_rlock(name: str, rec: Optional[Recorder] = None) -> TrackedRLock:
    return TrackedRLock(name, rec)


def tracked_condition(name: str, rec: Optional[Recorder] = None) -> TrackedCondition:
    return TrackedCondition(name, rec)


# ---------------------------------------------------------------------------
# collective-sequence funnel
# ---------------------------------------------------------------------------

#: flipped by enable() (or collective_recording) — parallel/collectives.py
#: calls record_collective on every accounted collective and this keeps
#: the un-sanitized fast path at one boolean check
_collectives_on = False
_collective_recorder: Optional[Recorder] = None


def record_collective(op: str, axis, shape, dtype) -> None:
    """Funnel for ``parallel/collectives._account``: no-op unless the
    sanitizer (or a scoped :func:`collective_recording`) is active."""
    if not _collectives_on:
        return
    rec = _collective_recorder if _collective_recorder is not None else recorder
    rec.record_collective(op, axis, shape, dtype)


class collective_recording:
    """Scoped recording into a throwaway recorder (unit tests / ad-hoc
    drivers) without globally instrumenting the flow layer."""

    def __init__(self, rec: Optional[Recorder] = None):
        self.rec = rec if rec is not None else Recorder()

    def __enter__(self) -> Recorder:
        global _collectives_on, _collective_recorder
        self._prev = (_collectives_on, _collective_recorder)
        _collectives_on = True
        _collective_recorder = self.rec
        return self.rec

    def __exit__(self, *exc):
        global _collectives_on, _collective_recorder
        _collectives_on, _collective_recorder = self._prev
        return False


# ---------------------------------------------------------------------------
# instrumentation
# ---------------------------------------------------------------------------

_enabled = False
_exit_checked = False


def enable(register_atexit: bool = True) -> None:
    """Instrument the flow layer (idempotent). Called automatically by
    tests/conftest.py when ``FLINK_ML_TPU_SANITIZE=1``; safe to call
    directly from a driver process."""
    global _enabled, _collectives_on
    if _enabled:
        return
    _enabled = True
    _collectives_on = True  # collectives._account starts feeding the ledger

    from .. import flow
    from ..obs import tracing

    orig_init = flow.BoundedChannel.__init__
    orig_close = flow.BoundedChannel.close
    orig_cancel = flow.BoundedChannel.cancel
    orig_pump = flow.pump
    orig_spawn = flow.spawn

    def init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        self._cv = TrackedCondition(
            f"flow.BoundedChannel._cv[{self.name}]", recorder, inner=self._cv
        )
        recorder.register_channel(self)

    def close(self, error=None):
        recorder.channel_closed(self)
        return orig_close(self, error=error)

    def cancel(self):
        recorder.channel_closed(self)
        return orig_cancel(self)

    def pump(items, channel, transform=None, watchdog=None):
        recorder.channel_pumped(channel)
        worker = orig_pump(items, channel, transform=transform, watchdog=watchdog)
        recorder.register_worker(worker, "pump")
        return worker

    def spawn(fn, name="worker"):
        worker = orig_spawn(fn, name=name)
        recorder.register_worker(worker, "spawn")
        return worker

    flow.BoundedChannel.__init__ = init
    flow.BoundedChannel.close = close
    flow.BoundedChannel.cancel = cancel
    flow.pump = pump
    flow.spawn = spawn
    # the obs tracing lock joins the DAG (the only other lock in the tree)
    tracing._lock = TrackedLock("obs.tracing._lock", recorder, inner=tracing._lock)

    if register_atexit:
        atexit.register(_atexit_check)


def mark_exit_checked() -> None:
    """A harness (pytest sessionfinish) already ran the exit check; the
    atexit fallback becomes a no-op."""
    global _exit_checked
    _exit_checked = True


def _atexit_check() -> None:
    if _exit_checked:
        return
    found = recorder.problems()
    if found:
        sys.stderr.write(
            "FLINK_ML_TPU_SANITIZE: concurrency violations at exit:\n"
            + "".join(f"  - {p}\n" for p in found)
        )
        sys.stderr.flush()
        os._exit(66)  # atexit cannot change the exit status any other way
    sys.stderr.write(
        "FLINK_ML_TPU_SANITIZE: clean "
        f"({recorder.stats()['acquisitions']} acquisitions, "
        f"{recorder.stats()['workers']} workers, "
        f"{recorder.stats()['channels']} channels, "
        f"{recorder.stats()['collectives']} collectives)\n"
    )
