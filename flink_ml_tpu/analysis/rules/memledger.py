"""Residency gate: long-lived device arrays born outside the ledger.

The upload-accounting rule keeps *flows* honest (every H2D byte rides the
accounted stager); this rule extends the same funnel contract to *stocks*:
a device array bound to a module-level name or a ``self.<attr>`` slot
lives for the process / object lifetime, and if it was created by a raw
device-array constructor (``jax.device_put``, ``jnp.zeros``, ...) instead
of an accounted funnel, the HBM ledger (obs/memledger.py) never sees it —
`hbm.live.*`, `peakHbmBytes`, budget admission and the OOM forensics all
under-report by exactly that allocation. Function-local device arrays are
out of scope (transients the GC reclaims with the frame); so is anything
staged through `stage_to_device`/`stage_from_callback` (tracked when a
category is declared), reached via `device_constants()`/the model store's
`page_in` (both stage every byte through the accounted path), or
explicitly `memledger.track`-ed.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional

from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name
from . import _astwalk
from .accounting import TRANSFER_PRIMITIVES

#: Array constructors on the jax.numpy namespace that allocate a fresh
#: device-resident array (views/dtype helpers are not creators).
NUMPY_CREATORS = frozenset(
    {
        "zeros",
        "ones",
        "full",
        "empty",
        "array",
        "asarray",
        "arange",
        "linspace",
        "eye",
        "zeros_like",
        "ones_like",
        "full_like",
    }
)

#: Call names that mean the binding IS ledgered (the accounted funnels
#: and the explicit tracking API) — their presence anywhere in the RHS
#: exempts the assignment.
FUNNEL_CALLS = frozenset(
    {
        "stage_to_device",
        "stage_from_callback",
        "track",
        "device_constants",
        # the ModelStore paging path: page_in stages every resident model
        # byte through device_constants() -> stage_to_device(category=
        # "model"), so a binding fed by it is ledgered by construction
        "page_in",
    }
)

_JAX_MODULES = {"jax"}
_NUMPY_MODULES = {"jax.numpy", "jnp"}


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> dotted module for every import in the file."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _creator_call(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """The dotted creator name when `node` is a raw device-array
    constructor call, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if not name or "." not in name:
        # bare call: resolve `from jax import device_put`-style imports
        resolved = aliases.get(name or "")
        if resolved and "." in resolved:
            mod, leaf = resolved.rsplit(".", 1)
            name = f"{mod}.{leaf}"
        else:
            return None
    head, leaf = name.rsplit(".", 1)
    head = aliases.get(head.split(".")[0], head.split(".")[0]) + (
        "." + head.split(".", 1)[1] if "." in head else ""
    )
    if head in _NUMPY_MODULES and leaf in NUMPY_CREATORS:
        return f"{head}.{leaf}"
    if head in _JAX_MODULES and leaf in TRANSFER_PRIMITIVES:
        return f"{head}.{leaf}"
    return None


def _rhs_exempt(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name and name.rsplit(".", 1)[-1] in FUNNEL_CALLS:
                return True
    return False


@register
class UnledgeredResidencyRule(Rule):
    id = "unledgered-residency"
    title = "long-lived device array created outside the accounted funnels"
    rationale = (
        "A device array bound to a module-level name or a self.<attr> slot "
        "is resident for the process/object lifetime, but one born from a "
        "raw constructor (jax.device_put, jnp.zeros, ...) never enters the "
        "HBM ledger — hbm.live.* gauges, peakHbmBytes, budget admission "
        "and the OOM forensic snapshot all under-report by that "
        "allocation. Route long-lived uploads through "
        "prefetch.stage_to_device(..., category=...) or ledger them with "
        "memledger.track; function-local transients are out of scope."
    )
    example = "self._centroids = jnp.zeros((k, d))  # use stage_to_device + category"
    scope = ("flink_ml_tpu",)
    # the analysis package only talks ABOUT these calls; obs/ implements
    # the ledger itself
    exclude = ("flink_ml_tpu/analysis", "flink_ml_tpu/obs/memledger.py")

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        tree = module.tree
        if tree is None:
            return
        aliases = _import_aliases(tree)

        def check_assign(stmt, binding: str) -> Iterable[Finding]:
            value = getattr(stmt, "value", None)
            if value is None or _rhs_exempt(value):
                return
            for sub in ast.walk(value):
                creator = _creator_call(sub, aliases)
                if creator is not None:
                    yield Finding(
                        path=module.path,
                        line=stmt.lineno,
                        rule=self.id,
                        message=(
                            f"{binding} binds a device array from raw "
                            f"{creator}(...) — a long-lived residency the "
                            "HBM ledger never sees (stage it with "
                            "prefetch.stage_to_device(..., category=...) "
                            "or memledger.track it)"
                        ),
                        data=(creator, binding),
                    )
                    return

        # module-level bindings (import-time residency, lives forever)
        for stmt in _astwalk.statements_in_order(tree.body):
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                yield from check_assign(stmt, "module-level name")

        # self.<attr> bindings (object-lifetime residency)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for target in targets:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    yield from check_assign(node, f"self.{target.attr}")
                    break
