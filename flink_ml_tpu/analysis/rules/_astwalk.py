"""Statement-ordered AST walking shared by the dataflow-ish rules.

Linear (source-order) statement walks need one invariant: a compound
statement contributes only its OWN header expressions (test, iter,
with-items); its nested blocks are yielded as separate statements. A rule
that walks a compound statement wholesale scans nested code twice and —
worse — out of order relative to the state it is tracking.
"""

from __future__ import annotations

import ast
from typing import List


def header_nodes(stmt: ast.stmt) -> List[ast.AST]:
    """The statement's own expressions, excluding nested statement blocks
    (and excluding nested function/class bodies, which are separate
    scopes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.target, stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: List[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if isinstance(
        stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        return []
    return [stmt]


def statements_in_order(body: List[ast.stmt]) -> List[ast.stmt]:
    """Every statement reachable from ``body``, linearized in source order;
    branch arms concatenate, loop back-edges are not modeled, nested
    function/class bodies are skipped (separate scopes)."""
    out: List[ast.stmt] = []

    def visit_block(stmts) -> None:
        for stmt in stmts:
            out.append(stmt)
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            for block in (
                getattr(stmt, "body", None),
                getattr(stmt, "orelse", None),
                getattr(stmt, "finalbody", None),
            ):
                if block:
                    visit_block(block)
            for handler in getattr(stmt, "handlers", []) or []:
                visit_block(handler.body)

    visit_block(body)
    return out
