"""tpulint rule modules. Importing this package registers every rule with
the engine registry (flink_ml_tpu.analysis.engine)."""

from . import (  # noqa: F401
    accounting,
    coverage,
    donation,
    flowcontrol,
    hostsync,
    retrace,
    shardingtags,
)
