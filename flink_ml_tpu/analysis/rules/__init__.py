"""tpulint rule modules. Importing this package registers every rule with
the engine registry (flink_ml_tpu.analysis.engine)."""

from . import (  # noqa: F401
    accounting,
    channelprotocol,
    coverage,
    divergence,
    donation,
    flowcontrol,
    hostsync,
    lockorder,
    memledger,
    meshaxis,
    precision,
    residentprogram,
    retrace,
    servepath,
    shardingtags,
    snapshotcommit,
    specconsistency,
    untimedwait,
)
