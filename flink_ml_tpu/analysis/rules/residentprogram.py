"""resident-program: host callbacks inside whole-fit program bodies.

The whole-fit work (parallel/dispatch.py, docs/performance.md "Whole-fit
resident programs") exists to make a fit exactly ONE host↔device round
trip — which a single ``io_callback`` / ``pure_callback`` /
``jax.debug.print`` / ``jax.debug.callback`` (or a stray builtin
``print``) inside the compiled loop silently destroys: each epoch of the
resident while-loop then re-enters the host, turning the one-dispatch
program back into a per-epoch tunnel conversation that no counter
accounts (callbacks bypass the ``packed_device_get`` funnels AND the
``iteration.host_sync`` budget). The rule flags host-callback calls that
are lexically inside a resident program body:

- any **jitted kernel** function (a ``lazy_jit``/``keyed_jit``/``jax.jit``
  bound or decorated def — resolved through the shared ``_jitindex``,
  including the ``NAME = lazy_jit(_impl, ...)`` binding idiom, where the
  body is ``_impl``), nested defs included;
- any local function **passed to a lax loop/branch combinator**
  (``lax.while_loop`` / ``fori_loop`` / ``scan`` / ``cond`` / ``switch``
  / ``map``) anywhere in a scoped module — loop bodies are resident by
  construction even when the enclosing jit wrapper lives elsewhere.

``jax.debug.print`` during interactive debugging is legitimate — which is
exactly why a committed one takes a ``# tpulint: disable=resident-program
-- <why this callback must ship>`` suppression or gets deleted.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name
from . import _jitindex

#: dotted-call suffixes that re-enter the host from inside a program
_CALLBACK_SUFFIXES = (
    "io_callback",
    "pure_callback",
    "debug.print",
    "debug.callback",
    "debug.breakpoint",
    "experimental.io_callback",
)

#: lax combinators whose function arguments become resident loop bodies
_LOOP_COMBINATORS = ("while_loop", "fori_loop", "scan", "cond", "switch", "map")


def _is_callback_call(node: ast.Call, info, imports: Dict[str, tuple]) -> str:
    """The callback's display name if `node` calls a host callback, else ''."""
    name = dotted_name(node.func)
    if name is None:
        return ""
    if name == "print":
        return "print"
    root, _, rest = name.partition(".")
    if root in info.jax_aliases and rest:
        for suffix in _CALLBACK_SUFFIXES:
            if rest == suffix or rest.endswith("." + suffix):
                return name
    # from jax.experimental import io_callback / from jax import pure_callback
    target = imports.get(root)
    if target is not None and rest == "":
        module, original = target
        if module.startswith("jax") and original in (
            "io_callback",
            "pure_callback",
        ):
            return f"{module}.{original}"
    return ""


def _is_vmap_call(node: ast.AST, info) -> bool:
    """True for `jax.vmap(...)` / `vmap(...)` (any jax alias / direct
    import) — a batching wrapper whose operand stays a resident body."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is None:
        return False
    root, _, rest = name.partition(".")
    if root in info.jax_aliases and rest == "vmap":
        return True
    target = info.imports.get(root)
    return (
        target is not None
        and rest == ""
        and target[0].startswith("jax")
        and target[1] == "vmap"
    )


def _unwrap_vmap_name(node: ast.AST, info) -> str:
    """The function NAME under any stack of vmap wrappers (`jax.vmap(f)`,
    `vmap(vmap(f))`, ...); '' when the operand is not a plain name.
    vmap changes batching, not residency — a vmapped while_loop body is
    still compiled into the one-dispatch program (fleet kernels)."""
    while _is_vmap_call(node, info):
        if not node.args:
            return ""
        node = node.args[0]
    return node.id if isinstance(node, ast.Name) else ""


def _loop_body_names(module: SourceModule, info) -> Set[str]:
    """Names of local functions passed positionally to a lax loop/branch
    combinator (their bodies run inside the compiled program) — seen
    through vmap wrappers (`lax.while_loop(vmap(cond), vmap(body), ...)`)."""
    names: Set[str] = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if fn is None:
            continue
        root, _, rest = fn.partition(".")
        is_lax = (root in info.lax_aliases and rest in _LOOP_COMBINATORS) or (
            root in info.jax_aliases
            and rest.startswith("lax.")
            and rest.split(".")[-1] in _LOOP_COMBINATORS
        )
        if not is_lax:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            else:
                unwrapped = _unwrap_vmap_name(arg, info)
                if unwrapped:
                    names.add(unwrapped)
    return names


def _kernel_impl_names(module: SourceModule, info) -> Set[str]:
    """Function names whose defs ARE jitted-kernel bodies: decorated defs
    plus the first positional argument of a `NAME = lazy_jit(impl, ...)` /
    `jax.jit(impl, ...)` module-level binding — including a vmap-wrapped
    impl (`NAME = lazy_jit(jax.vmap(impl), ...)`, the fleet-kernel
    idiom)."""
    names: Set[str] = set(info.kernels)
    for node in module.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id in info.kernels
            and isinstance(node.value, ast.Call)
            and node.value.args
        ):
            arg0 = node.value.args[0]
            if isinstance(arg0, ast.Name):
                names.add(arg0.id)
            else:
                unwrapped = _unwrap_vmap_name(arg0, info)
                if unwrapped:
                    names.add(unwrapped)
    return names


@register
class ResidentProgramRule(Rule):
    id = "resident-program"
    title = "host callback inside a resident (whole-fit) program body"
    rationale = (
        "A whole-fit resident program is ONE dispatch and ONE packed "
        "readback; an io_callback/pure_callback/jax.debug.print inside "
        "its loop body re-enters the host EVERY epoch — an unaccounted "
        "per-epoch sync that resurrects the dispatch wall the resident "
        "path exists to kill, invisible to hostSyncCount. Keep program "
        "bodies callback-free, or suppress WITH the reason the callback "
        "must ship."
    )
    example = "jax.debug.print('epoch {e}', e=epoch)  # inside a while_loop body"
    scope = ("flink_ml_tpu",)

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        info = _jitindex.jit_index(project)[module.path]
        resident_names = _kernel_impl_names(module, info) | _loop_body_names(
            module, info
        )
        findings: List[Finding] = []
        seen = set()

        def scan(fn_node: ast.AST, owner: str) -> None:
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                callback = _is_callback_call(node, info, info.imports)
                if not callback:
                    continue
                key = (node.lineno, callback)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            f"{callback} inside resident program body "
                            f"{owner}() re-enters the host every epoch — "
                            "an unaccounted per-epoch sync inside a "
                            "one-dispatch program; move it outside the "
                            "compiled loop or suppress with the reason "
                            "it must ship"
                        ),
                        data=("callback", callback, owner),
                    )
                )

        for node in ast.walk(module.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in resident_names
            ):
                scan(node, node.name)
        return findings
