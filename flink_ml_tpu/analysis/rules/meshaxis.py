"""mesh-axis: every collective axis name must exist and ride a constant.

The upcoming 2D-mesh rebuild of ``parallel/mesh.py`` adds a second axis
name to every spec and axis-restricted collective in the training
programs. An axis-name typo does not fail fast: ``psum(x, "dta")``
errors only when the program is traced under a mesh — possibly a
production mesh an hour into a job — and a *valid-but-wrong* axis name
(``"model"`` where ``"data"`` was meant) silently reduces over the wrong
dimension of the machine. The rule leans on the SPMD layer
(``analysis/spmd.py``):

1. **unknown axis** — an axis literal at a collective call, inside a
   ``P(...)`` spec, or in a ``create_mesh``/``Mesh`` axis tuple that no
   ``*_AXIS`` constant in ``parallel/mesh.py`` declares;
2. **constant bypass** — a literal that duplicates a declared constant
   (``"data"`` instead of ``DATA_AXIS``): renaming an axis would miss
   it, and the 2D-mesh PR renames axes;
3. **unsharded collective** — a gather/permute over an axis the abstract
   operand does not vary on (the interpreter propagates in_specs through
   the shard_map body): the collective moves bytes to replicate what was
   already replicated, or — worse — the spec is wrong.
"""

from __future__ import annotations

from typing import Iterable

from .. import spmd
from ..engine import Finding, Rule, register


@register
class MeshAxisRule(Rule):
    id = "mesh-axis"
    title = "collective/spec axis name unknown, literal, or unsharded"
    rationale = (
        "An axis-name typo surfaces only when the program traces under a "
        "mesh — the worst moment — and a valid-but-wrong axis silently "
        "reduces over the wrong dimension of the machine. Axis names are "
        "declared ONCE as *_AXIS constants in parallel/mesh.py; every "
        "collective call, P(...) spec, and mesh construction must use the "
        "constants (a literal would survive an axis rename), name a "
        "declared axis, and gather/permute only over axes the operand is "
        "actually sharded on."
    )
    example = 'grad = all_reduce_sum(grad, "dta")  # unknown axis, literal'
    scope = ("flink_ml_tpu",)

    def check_project(self, project) -> Iterable[Finding]:
        interp = spmd.interpretation(project)
        reg = spmd.axis_registry(project)
        known = ", ".join(sorted(reg.known_axes)) or "<none declared>"
        for event in interp.of_kind("unknown-axis"):
            if not self.applies_to(event.path):
                continue
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"axis name {event.detail!r} is not declared by any "
                    f"*_AXIS constant in parallel/mesh.py (known: {known}) "
                    "— this traces only under a mesh that happens to have "
                    "it, and fails (or silently mis-reduces) everywhere else"
                ),
                data=("unknown-axis", event.detail),
            )
        for event in interp.of_kind("axis-bypass"):
            if not self.applies_to(event.path):
                continue
            const = event.extra[0] if event.extra else ""
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"string literal {event.detail!r} bypasses the "
                    f"{const or '*_AXIS'} constant (parallel/mesh.py) — an "
                    "axis rename in the 2D-mesh work would silently miss "
                    "this site; import the constant instead"
                ),
                data=("axis-bypass", event.detail, const),
            )
        for event in interp.of_kind("unsharded-collective"):
            if not self.applies_to(event.path):
                continue
            axis = event.extra[0] if event.extra else "?"
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"{event.detail} over axis {axis!r} but the operand is "
                    "not sharded on that axis (per the in_specs the "
                    "interpreter propagated) — the collective replicates a "
                    "replica, which means either wasted wire bytes or a "
                    "wrong PartitionSpec"
                ),
                data=("unsharded-collective", event.detail, axis),
            )
