"""sharding-tags: checkpoint spec tags must be stageable on a mesh.

A JobSnapshot leaf carries a sharding-spec tag (``replicated`` / ``data``
/ ``model`` / ``host``) that ``ckpt/snapshot.py:stage_section`` resolves
against ``parallel/mesh.py``'s spec constructors at RESTORE time — which
is the worst possible moment to discover a typo: the fit that wrote the
snapshot is gone, and the resume (possibly on a different device count;
that is the elastic contract) refuses the file. This rule checks the
consistency chain statically, at the lint step:

1. the literal tag table (``_SPEC_TAGS`` in snapshot.py) is the single
   source of truth;
2. every non-host tag in it must have a ``<tag>_sharding`` constructor in
   parallel/mesh.py AND be dispatched by snapshot.py's ``_sharding_for``;
3. every literal tag at a ``save_job_snapshot(..., specs=...)`` /
   ``stage_section(..., specs=...)`` call site anywhere in the package
   must name a tag from the table (dict KEYS are section names and are
   not checked; simple local-variable indirection — the ``carry_specs``
   idiom — is followed one assignment deep).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name

SNAPSHOT_PATH = "flink_ml_tpu/ckpt/snapshot.py"
MESH_PATH = "flink_ml_tpu/parallel/mesh.py"
SPEC_TABLE_NAME = "_SPEC_TAGS"
# "host" leaves stay numpy — staged by identity, no mesh constructor
NON_MESH_TAGS = {"host"}
ENTRY_POINTS = ("save_job_snapshot", "stage_section")


def _literal_strings(node: ast.AST) -> Iterable[Tuple[str, int]]:
    """(string, line) for every literal tag inside a specs expression —
    skipping dict KEYS (they are section names, not tags)."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            yield node.value, node.lineno
    elif isinstance(node, ast.Dict):
        for value in node.values:
            yield from _literal_strings(value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            yield from _literal_strings(elt)
    elif isinstance(node, ast.BinOp):
        # ("replicated",) * len(x) and friends
        yield from _literal_strings(node.left)
        yield from _literal_strings(node.right)
    elif isinstance(node, ast.IfExp):
        yield from _literal_strings(node.body)
        yield from _literal_strings(node.orelse)
    elif isinstance(node, ast.Starred):
        yield from _literal_strings(node.value)


def _spec_table(snapshot_module: SourceModule) -> Tuple[Set[str], int]:
    """The _SPEC_TAGS literals and the line they are declared on."""
    if snapshot_module.tree is None:
        return set(), 1
    for node in snapshot_module.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == SPEC_TABLE_NAME
        ):
            tags = {s for s, _ in _literal_strings(node.value)}
            return tags, node.lineno
    return set(), 1


def _dispatched_tags(snapshot_module: SourceModule) -> Set[str]:
    """Tags `_sharding_for` explicitly compares against (its trailing
    return is the replicated fallback)."""
    tags: Set[str] = set()
    if snapshot_module.tree is None:
        return tags
    for node in ast.walk(snapshot_module.tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "_sharding_for"
        ):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Compare):
                    for comp in [sub.left] + list(sub.comparators):
                        if isinstance(comp, ast.Constant) and isinstance(
                            comp.value, str
                        ):
                            tags.add(comp.value)
    return tags


def _mesh_constructors(mesh_module: SourceModule) -> Set[str]:
    """Tags for which parallel/mesh.py defines `<tag>_sharding`."""
    out: Set[str] = set()
    if mesh_module.tree is None:
        return out
    for node in mesh_module.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name.endswith("_sharding"):
            out.add(node.name[: -len("_sharding")])
    return out


@register
class ShardingTagRule(Rule):
    id = "sharding-tags"
    title = "checkpoint sharding tag is not stageable"
    rationale = (
        "Snapshot leaf tags are resolved against parallel/mesh.py's spec "
        "constructors at RESTORE time — a tag the mesh cannot stage turns "
        "a recoverable preemption into an unrecoverable refusal, "
        "discovered only when the original fit is already gone. The tag "
        "table, the stage_section dispatch, the mesh constructors, and "
        "every literal tag at a save/stage call site must agree."
    )
    example = 'save_job_snapshot(..., specs={"model": "fully_sharded"})'
    scope = ("flink_ml_tpu",)

    def check_project(self, project) -> Iterable[Finding]:
        snapshot_module = project.module_at(SNAPSHOT_PATH)
        mesh_module = project.module_at(MESH_PATH)
        if snapshot_module is None or mesh_module is None:
            return  # subsystem absent; nothing to hold consistent
        tags, table_line = _spec_table(snapshot_module)
        if not tags:
            yield Finding(
                path=SNAPSHOT_PATH,
                line=table_line,
                rule=self.id,
                message=(
                    f"cannot locate the literal {SPEC_TABLE_NAME} spec table "
                    "— the sharding-tag consistency chain is unanchored"
                ),
            )
            return

        dispatched = _dispatched_tags(snapshot_module) | {"replicated"}
        constructors = _mesh_constructors(mesh_module) | NON_MESH_TAGS
        for tag in sorted(tags):
            if tag not in constructors:
                yield Finding(
                    path=MESH_PATH,
                    line=1,
                    rule=self.id,
                    message=(
                        f"spec tag {tag!r} (ckpt/snapshot.py {SPEC_TABLE_NAME}) "
                        f"has no {tag}_sharding constructor in parallel/mesh.py "
                        "— stage_section cannot resolve it on any mesh"
                    ),
                    data=(tag,),
                )
            if tag not in dispatched and tag not in NON_MESH_TAGS:
                yield Finding(
                    path=SNAPSHOT_PATH,
                    line=table_line,
                    rule=self.id,
                    message=(
                        f"spec tag {tag!r} is in {SPEC_TABLE_NAME} but "
                        "_sharding_for never dispatches it — restores would "
                        "silently fall back to replicated"
                    ),
                    data=(tag,),
                )

        # call sites across the package
        for module in project.modules:
            if module.tree is None:
                continue
            yield from self._check_call_sites(module, tags)

    def _check_call_sites(
        self, module: SourceModule, tags: Set[str]
    ) -> Iterable[Finding]:
        # simple one-deep local indirection: name -> literal tags
        local_literals: Dict[str, List[Tuple[str, int]]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    found = list(_literal_strings(node.value))
                    if found:
                        local_literals[target.id] = found
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or name.split(".")[-1] not in ENTRY_POINTS:
                continue
            for kw in node.keywords:
                if kw.arg != "specs":
                    continue
                value = kw.value
                candidates = list(_literal_strings(value))
                if isinstance(value, ast.Name):
                    candidates = local_literals.get(value.id, [])
                elif isinstance(value, ast.Dict):
                    # dict values may themselves be local names
                    for v in value.values:
                        if isinstance(v, ast.Name):
                            candidates += local_literals.get(v.id, [])
                for tag, line in candidates:
                    if tag not in tags:
                        yield Finding(
                            path=module.path,
                            line=line,
                            rule=self.id,
                            message=(
                                f"unknown sharding-spec tag {tag!r} — "
                                f"not in ckpt/snapshot.py {SPEC_TABLE_NAME} "
                                f"({', '.join(sorted(tags))}); stage_section "
                                "would refuse this snapshot at restore time"
                            ),
                            data=(tag,),
                        )
