"""collective-divergence: collectives under shard-varying control flow.

A collective only completes when *every* participant along the axis
issues it. Inside a ``shard_map`` body, a branch whose condition varies
per shard — an ``axis_index`` comparison, a test on sharded data, a
``while_loop`` whose trip count depends on a per-shard value — lets some
shards reach the collective while others skip it. On the single-host
virtual-device mesh this usually traces into *one* program and the
hazard hides; on a real multi-host DCN mesh each host traces and runs
its own copy of the python, and the mismatch is a **deadlock**: the
fast hosts park in the collective forever while the slow ones never
arrive. That is precisely the failure mode the 2D-mesh + multi-host
work (ROADMAP item 1) cannot debug numerically — a hung job has no
numbers.

The static detector is the interpreter's divergence context: entering
an ``if``/``while``/``for`` whose test varies per shard (or a
``lax.cond``/``switch``/``while_loop`` whose predicate does) marks the
region, and any reduce/gather/permute reached inside it is flagged with
both the collective's line and the branching line. The dynamic dual is
the sanitizer's collective-sequence recorder
(``FLINK_ML_TPU_SANITIZE=1``): it records the per-shard (op, axis,
shape, dtype) sequence and fails at exit on cross-shard divergence —
each side covers the other's blind spot.

The sanctioned shape for rank-dependent communication is data-dependent
*content* with rank-independent *structure*: every shard issues the same
collective and masks its contribution (weight 0, zero padding), exactly
how the padded-batch convention already works.
"""

from __future__ import annotations

from typing import Iterable

from .. import spmd
from ..engine import Finding, Rule, register


@register
class CollectiveDivergenceRule(Rule):
    id = "collective-divergence"
    title = "collective reachable under a shard-varying branch"
    rationale = (
        "A collective completes only when every shard along the axis "
        "issues it; a branch that varies per shard (axis_index tests, "
        "conditions on sharded data, data-dependent loop trip counts) "
        "lets some shards skip it. Single-host tracing hides the bug; a "
        "multi-host DCN mesh turns it into a deadlock with no error "
        "message. Keep the collective STRUCTURE uniform and make the "
        "contribution data-dependent instead (mask with weight 0 / zero "
        "padding, the padded-batch convention)."
    )
    example = "if axis_index(DATA_AXIS) == 0:\n    x = all_reduce_sum(x, DATA_AXIS)"
    scope = ("flink_ml_tpu",)

    def check_project(self, project) -> Iterable[Finding]:
        interp = spmd.interpretation(project)
        for event in interp.of_kind("divergent-collective"):
            if not self.applies_to(event.path):
                continue
            branch_line = event.extra[0] if event.extra else "?"
            reason = event.extra[1] if len(event.extra) > 1 else "shard-varying branch"
            axis = event.extra[2] if len(event.extra) > 2 else "?"
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"{event.detail} over axis {axis!r} is reachable only "
                    f"under the branch at line {branch_line} ({reason}) — "
                    "shards that take the other arm never issue the "
                    "collective, which deadlocks a multi-host mesh; issue "
                    "it unconditionally and mask the contribution instead"
                ),
                data=("divergent", event.detail, branch_line),
            )
