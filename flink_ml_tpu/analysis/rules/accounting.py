"""Accounting gates: raw collectives / raw transfers in models and ops.

These are the two oldest tpulint rules, ported from the standalone
``scripts/check_collective_accounting.py`` and
``scripts/check_upload_accounting.py`` gates (which remain as thin shims
over these rules). Both enforce the same economic invariant: a byte that
moves without being counted makes every BENCH field that sums bytes a
lie. Scanning is over the comment/string-stripped source (the shared
``analysis.source.code_only``), so docstrings that merely *mention* a
primitive stay legal.
"""

from __future__ import annotations

import re
from typing import Iterable

from ..engine import Finding, Rule, register
from ..source import SourceModule

# the surfaces the accounted wrappers cover (keep in sync with
# parallel/collectives.py and parallel/prefetch.py)
COLLECTIVE_PRIMITIVES = (
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "ppermute",
)
TRANSFER_PRIMITIVES = (
    "device_put",
    "device_put_sharded",
    "device_put_replicated",
    "make_array_from_callback",
    "make_array_from_single_device_arrays",
)


class _PatternRule(Rule):
    """Regex-over-stripped-source rule; findings carry the matched
    primitive in ``data`` for the legacy shims."""

    pattern: re.Pattern = None  # type: ignore[assignment]
    message_fmt: str = ""

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        for i, line in enumerate(module.stripped_lines(), start=1):
            for match in self.pattern.finditer(line):
                primitive = match.group(1)
                yield Finding(
                    path=module.path,
                    line=i,
                    rule=self.id,
                    message=self.message_fmt.format(primitive=primitive),
                    data=(primitive,),
                )


@register
class CollectiveAccountingRule(_PatternRule):
    id = "collective-accounting"
    title = "raw lax collective bypasses the accounted wrappers"
    rationale = (
        "Every collective a model or op dispatches must ride the accounted "
        "wrappers in parallel/collectives.py — that is what keeps the "
        "`collective.*` counters (and the BENCH `collectiveBreakdown` "
        "field) an exhaustive answer to 'what traffic does this program "
        "move'. A raw `lax.psum` would execute fine and silently vanish "
        "from the accounting. GSPMD-inserted collectives are invisible to "
        "source scanning and intentionally out of scope."
    )
    example = "grad = lax.psum(grad, axis_name)  # use collectives.all_reduce_sum"
    scope = ("flink_ml_tpu/models", "flink_ml_tpu/ops")
    pattern = re.compile(
        r"\blax\s*\.\s*(" + "|".join(COLLECTIVE_PRIMITIVES) + r")\s*\("
    )
    message_fmt = (
        "lax.{primitive}(...) bypasses the accounted collective wrappers "
        "(use flink_ml_tpu.parallel.collectives instead)"
    )


@register
class UploadAccountingRule(_PatternRule):
    id = "upload-accounting"
    title = "raw host->device transfer bypasses the accounted stager"
    rationale = (
        "Every host->device upload a model or op makes must ride the "
        "accounted stager in parallel/prefetch.py (`stage_to_device` / "
        "`stage_from_callback`) — that is what keeps `h2d.bytes`/`h2d.count` "
        "(and the BENCH `h2dBytes` field, and the inputPipeline entry's "
        "zero-upload-epochs claim) exhaustive. The upload-side mirror of "
        "collective-accounting; implicit jit-argument transfers are out of "
        "scope — the bulk data paths all stage explicitly."
    )
    example = "X_dev = jax.device_put(X)  # use prefetch.stage_to_device"
    scope = ("flink_ml_tpu/models", "flink_ml_tpu/ops")
    pattern = re.compile(
        r"\bjax\s*\.\s*(" + "|".join(TRANSFER_PRIMITIVES) + r")\s*\("
    )
    message_fmt = (
        "jax.{primitive}(...) bypasses the accounted host->device stager "
        "(use flink_ml_tpu.parallel.prefetch.stage_to_device instead)"
    )
