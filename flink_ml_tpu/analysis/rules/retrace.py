"""retrace-hazard: compile-cache-busting jit usage.

BENCH_r05's dispatch-bound verdict makes every stray recompile a
wall-clock cliff: over this environment's remote-compile tunnel a single
retrace costs seconds, and a jit wrapper constructed per call retraces on
*every* call. The rule pins three hazard shapes:

- **raw ``jax.jit``** anywhere outside ``utils/lazyjit.py``: even when a
  module-level wrapper reuses its cache, it bypasses the ``jit.kernels``
  counter (and the hook install) that keeps compile accounting
  exhaustive — route through ``lazy_jit`` / ``keyed_jit``.
- **jitted closures over local state**: ``lazy_jit``/``jax.jit`` applied
  (inside a function) to a lambda or nested def that captures enclosing
  locals — a NEW wrapper per outer call, so nothing is ever reused, and
  hyperparameters captured as closure constants force a retrace per
  value (the packed-hparam vector exists precisely to make them runtime
  operands).
- **non-hashable static args**: f-strings or dict displays feeding
  ``static_argnums``/``static_argnames`` values — every call builds a
  fresh static key (or fails to hash), so the compile cache never hits.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name
from . import _jitindex


def _assigned_names(node: ast.AST) -> Set[str]:
    """Names bound anywhere inside ``node`` (params, assignments, defs)."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(sub.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            out.add(sub.name)
        elif isinstance(sub, ast.arg):
            out.add(sub.arg)
    return out


def _loaded_names(node: ast.AST) -> Set[str]:
    return {
        sub.id
        for sub in ast.walk(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
    }


@register
class RetraceHazardRule(Rule):
    id = "retrace-hazard"
    title = "jit usage that busts the compile cache or its accounting"
    rationale = (
        "A jit wrapper constructed per call retraces per call (seconds "
        "each over the remote-compile tunnel), and raw jax.jit — even "
        "module-level — bypasses the jit.kernels counter that keeps "
        "compile accounting exhaustive. Route kernels through "
        "utils/lazyjit.py; pack hyperparameters into runtime operands "
        "instead of closure constants; keep static_argnums keys hashable "
        "and stable."
    )
    example = "fn = jax.jit(step)  # use lazy_jit(step) — counted + reused"
    scope = ("flink_ml_tpu",)
    # the two accounted jit funnels: lazyjit installs the compile hooks
    # and counts kernels/traces; compilebank AOT-compiles through the
    # same traced wrappers (its jit.jit().lower().compile() is the bank
    # backfill path, accounted under bank.* + jit.traces)
    exclude = (
        "flink_ml_tpu/utils/lazyjit.py",
        "flink_ml_tpu/compilebank.py",
    )

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        info = _jitindex.jit_index(project)[module.path]
        findings: List[Finding] = []

        # --- raw jax.jit references ---------------------------------------
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id in info.jax_aliases
            ):
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            "raw jax.jit bypasses utils/lazyjit.py — the "
                            "jit.kernels counter (and hook install) misses "
                            "this wrapper; use lazy_jit/keyed_jit"
                        ),
                        data=("raw-jit",),
                    )
                )

        # --- non-hashable static_argnums feeds ----------------------------
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw in node.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                for sub in ast.walk(kw.value):
                    if isinstance(sub, (ast.JoinedStr, ast.Dict, ast.DictComp)):
                        findings.append(
                            Finding(
                                path=module.path,
                                line=sub.lineno,
                                rule=self.id,
                                message=(
                                    f"{kw.arg} fed a "
                                    f"{'f-string' if isinstance(sub, ast.JoinedStr) else 'dict'}"
                                    " — per-call static keys never hit the "
                                    "compile cache"
                                ),
                                data=("static-key",),
                            )
                        )

        # --- jitted closures over enclosing locals ------------------------
        # each call is judged against its INNERMOST enclosing function so
        # nested defs don't double-report
        for node, func in _calls_with_enclosing_function(module.tree):
            if not node.args:
                continue
            is_jit = info.is_jit_callable(node.func) or (
                dotted_name(node.func) in ("partial", "functools.partial")
                and info.is_jit_callable(node.args[0])
            )
            if not is_jit:
                continue
            wrapped = node.args[0]
            local_defs = {
                n.name: n
                for n in ast.walk(func)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not func
            }
            if isinstance(wrapped, ast.Lambda):
                target = wrapped
            elif isinstance(wrapped, ast.Name) and wrapped.id in local_defs:
                target = local_defs[wrapped.id]
            else:
                continue
            captured = (
                _loaded_names(target) - _assigned_names(target)
            ) & _assigned_names(func)
            if captured:
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            "jitted closure captures enclosing locals "
                            f"({', '.join(sorted(captured)[:4])}) — a new "
                            "wrapper traces per outer call; hoist the "
                            "kernel to module scope and pass captured "
                            "state as (packed) runtime operands"
                        ),
                        data=("closure",),
                    )
                )
        return findings


def _calls_with_enclosing_function(tree: ast.AST):
    """(Call, innermost enclosing FunctionDef) pairs, each call once."""
    out = []

    def visit(node: ast.AST, func) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node
        if isinstance(node, ast.Call) and func is not None:
            out.append((node, func))
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, None)
    return out
