"""Contract-coverage gates: fusion and checkpoint declarations.

Ported from ``scripts/check_fusion_coverage.py`` and
``scripts/check_checkpoint_coverage.py`` (which remain as thin shims).
Unlike the text rules these import the package and walk the live class
graph — a contract declared via inheritance or metaclass tricks is still
a declaration, and source scanning cannot see that. Findings anchor to
the class definition line so suppressions (never needed so far — these
gates stay at zero by declaration, not annotation) and editors can jump.

Both rules enforce the same shape of invariant: an opt-in protocol plus a
silent default equals silently-wrong new code, so every concrete class
must either opt in or explain why not.
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
from typing import Iterable, List, Tuple

from ..engine import Finding, Rule, register
from ..source import code_only

# ways a fit path reaches the JobSnapshot API; referenced from the
# estimator's own module (directly or through the shared SGD wiring)
CHECKPOINT_FUNNELS = (
    "run_sgd",
    "optimize_stream",
    "iterate_unbounded",
    "save_job_snapshot",
    "load_job_snapshot",
)


def _iter_operator_classes(base_name: str):
    """Every concrete subclass of api.<base_name> defined in the package."""
    import flink_ml_tpu
    from flink_ml_tpu import api

    base = getattr(api, base_name)
    seen = set()
    for info in pkgutil.walk_packages(
        flink_ml_tpu.__path__, flink_ml_tpu.__name__ + "."
    ):
        # extension build tree and CLI entrypoints are not stage modules
        # (importing a __main__ runs its CLI side effects)
        if ".native" in info.name or info.name.endswith("__main__"):
            continue
        try:
            module = importlib.import_module(info.name)
        except Exception as e:  # pragma: no cover - import rot is its own bug
            raise RuntimeError(f"cannot import {info.name}: {e!r}") from e
        for _, cls in inspect.getmembers(module, inspect.isclass):
            if (
                issubclass(cls, base)
                and not inspect.isabstract(cls)
                and cls.__module__ == module.__name__
                and cls not in seen
            ):
                seen.add(cls)
                yield cls


def _class_location(project, cls) -> Tuple[str, int]:
    try:
        abspath = inspect.getsourcefile(cls)
        line = inspect.getsourcelines(cls)[1]
    except (TypeError, OSError):  # pragma: no cover
        return "flink_ml_tpu", 1
    return os.path.relpath(abspath, project.root).replace("\\", "/"), line


def find_fusion_violations() -> List[Tuple[str, str]]:
    """(qualified class name, problem) pairs — the legacy gate payload."""
    from flink_ml_tpu.api import AlgoOperator

    violations = []
    for cls in _iter_operator_classes("AlgoOperator"):
        has_kernel = cls.transform_kernel is not AlgoOperator.transform_kernel
        # `fusable` must be declared on the class itself (or an own base
        # that overrode the AlgoOperator default) — inheriting the bare
        # default means nobody made the call for this stage
        declared = any(
            "fusable" in k.__dict__ for k in cls.__mro__[:-1] if k is not AlgoOperator
        )
        name = f"{cls.__module__}.{cls.__name__}"
        if has_kernel:
            if (
                not getattr(cls, "fusable", False)
                and cls.__dict__.get("supports_fusion") is None
                and not declared
            ):
                violations.append(
                    (name, "has transform_kernel but fusable is not declared True")
                )
            continue
        if not declared:
            violations.append(
                (name, "no transform_kernel and no explicit fusable declaration")
            )
            continue
        if getattr(cls, "fusable", False):
            violations.append(
                (name, "fusable = True but transform_kernel is not overridden")
            )
            continue
        reason = getattr(cls, "fusable_reason", "")
        if not isinstance(reason, str) or not reason.strip():
            violations.append(
                (name, "fusable = False without a non-empty fusable_reason")
            )
    return violations


def count_operator_classes() -> int:
    return len(list(_iter_operator_classes("AlgoOperator")))


def find_checkpoint_violations() -> List[Tuple[str, str]]:
    """(qualified class name, problem) pairs — the legacy gate payload."""
    from flink_ml_tpu.api import Estimator

    violations = []
    for cls in _iter_operator_classes("Estimator"):
        name = f"{cls.__module__}.{cls.__name__}"
        declared = any(
            "checkpointable" in k.__dict__
            for k in cls.__mro__[:-1]
            if k is not Estimator
        )
        if not declared:
            violations.append((name, "no explicit checkpointable declaration"))
            continue
        if getattr(cls, "checkpointable", None):
            if not _module_references_funnel(cls):
                violations.append(
                    (
                        name,
                        "checkpointable = True but its module references no "
                        f"checkpoint funnel ({', '.join(CHECKPOINT_FUNNELS)})",
                    )
                )
            continue
        reason = getattr(cls, "checkpoint_reason", "")
        if not isinstance(reason, str) or not reason.strip():
            violations.append(
                (name, "checkpointable = False without a non-empty checkpoint_reason")
            )
    return violations


def count_estimator_classes() -> int:
    return len(list(_iter_operator_classes("Estimator")))


def _module_references_funnel(cls) -> bool:
    """Funnel references on comment/string-stripped source, so a docstring
    that merely *mentions* `run_sgd` does not satisfy the True contract."""
    path = inspect.getsourcefile(cls)
    if path is None:  # pragma: no cover
        return False
    with open(path) as f:
        code = code_only(f.read())
    return any(funnel in code for funnel in CHECKPOINT_FUNNELS)


class _CoverageRule(Rule):
    requires_import = True
    finder = None  # staticmethod returning (name, problem) pairs

    def check_project(self, project) -> Iterable[Finding]:
        by_name = {}
        for cls in _iter_operator_classes(self.base_name):
            by_name[f"{cls.__module__}.{cls.__name__}"] = cls
        for name, problem in type(self).finder():
            cls = by_name.get(name)
            path, line = (
                _class_location(project, cls) if cls else ("flink_ml_tpu", 1)
            )
            yield Finding(
                path=path,
                line=line,
                rule=self.id,
                message=f"{name}: {problem}",
                data=(name, problem),
            )


@register
class FusionCoverageRule(_CoverageRule):
    id = "fusion-coverage"
    title = "stage does not declare its fusion contract"
    rationale = (
        "The transform-kernel protocol (api.py) is opt-in, so a newly added "
        "stage silently lands on the eager per-stage path — exactly the "
        "per-stage dispatch overhead the fusion planner exists to remove. "
        "Every concrete AlgoOperator must override transform_kernel (with "
        "fusable = True) or set fusable = False with a non-empty "
        "fusable_reason saying WHY it cannot run inside a fused program."
    )
    example = "class MyStage(AlgoOperator):  # neither kernel nor fusable declared"
    base_name = "AlgoOperator"
    finder = staticmethod(find_fusion_violations)


@register
class CheckpointCoverageRule(_CoverageRule):
    id = "checkpoint-coverage"
    title = "estimator does not declare its checkpoint contract"
    rationale = (
        "The JobSnapshot subsystem (ckpt/) makes preemption-safe resume a "
        "property of fit paths routed through it; an estimator that is not "
        "silently loses training progress on any preemption. Every concrete "
        "Estimator must set checkpointable = True (and its module must "
        "actually reference a sanctioned funnel — a bare True with no "
        "wiring is a lie the gate rejects) or False with a non-empty "
        "checkpoint_reason."
    )
    example = "class MyEstimator(Estimator):  # no checkpointable declaration"
    base_name = "Estimator"
    finder = staticmethod(find_checkpoint_violations)
