"""donation-after-use: reading a buffer after donating it.

The ``supports_donation()``-gated kernel variants (``*_donating``,
``donate_argnums=...``) let XLA reuse an argument's HBM in place — after
the call the Python name still points at a buffer whose contents are
gone. On CPU donation is a silent no-op, so a read-after-donate bug
passes every CPU test and corrupts results only on the TPU backend:
exactly the class of hazard that must be held statically.

The rule resolves donating kernels from the shared jit index (module
level ``N = jax.jit(f, donate_argnums=...)`` bindings, ``@partial(jax.jit,
donate_argnums=...)`` decorators, and one-hop imports of either), follows
the repo's selection idiom

    step = _sgd_chunk_donating if donate_ok else _sgd_chunk

and then walks each function linearly: a plain-name argument in a donated
position is dead after the call statement; any later load of that name
before a rebind is a finding. Donated names rebound by the call statement
itself (the ping-pong carry idiom) are fine. Calls with ``*args`` before
a donated position are skipped — positions are unknowable statically.

Since v2 the rule is interprocedural: the project call graph
(``analysis/callgraph.py``) summarizes which functions pass their own
parameters into donated positions — so a *wrapper* around a donating
kernel donates its caller's buffer too, and reading after the wrapper
call is flagged with the wrapper→kernel chain in the finding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import callgraph
from ..engine import Finding, Rule, register
from ..source import SourceModule
from . import _jitindex
from ._astwalk import header_nodes as _header_nodes
from ._astwalk import statements_in_order


def _donating_alias(
    kernels: Dict[str, Tuple[Tuple[int, ...], Optional[str]]], value: ast.AST
) -> Optional[Tuple[Tuple[int, ...], Optional[str]]]:
    """The ``(positions, chain label)`` entry if ``value`` may evaluate to
    a donating kernel (a bare name, or either arm of the donation-gating
    IfExp idiom)."""
    if isinstance(value, ast.Name):
        entry = kernels.get(value.id)
        if entry and entry[0]:
            return entry
        return None
    if isinstance(value, ast.IfExp):
        for arm in (value.body, value.orelse):
            entry = _donating_alias(kernels, arm)
            if entry:
                return entry
    return None


def _stored_names(stmt: ast.stmt) -> Set[str]:
    out = set()
    for header in _header_nodes(stmt):
        for sub in ast.walk(header):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, (ast.Store, ast.Del)):
                out.add(sub.id)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        out.add(stmt.name)
    return out


def _loaded_names_with_lines(stmt: ast.stmt) -> List[Tuple[str, int]]:
    out = []
    for header in _header_nodes(stmt):
        for sub in ast.walk(header):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.append((sub.id, sub.lineno))
    return out


@register
class DonationAfterUseRule(Rule):
    id = "donation-after-use"
    title = "donated buffer read after the donating call"
    rationale = (
        "donate_argnums hands the argument's HBM to XLA for in-place "
        "reuse; the Python name then references freed storage. CPU "
        "backends ignore donation, so the bug is invisible to CPU tests "
        "and real on TPU — reads after a donating call must either use "
        "the call's results or re-materialize the value first."
    )
    example = (
        "carry2, _ = _sgd_chunk_donating(X, y, w, carry, crit, ...)\n"
        "loss_of(carry)  # carry was donated (argnum 3): buffer is gone"
    )
    scope = ("flink_ml_tpu",)

    #: consult callee summaries for wrapper-level donation (False = the
    #: tpulint v1 per-function recall baseline)
    interprocedural = True

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        info = _jitindex.jit_index(project)[module.path]
        donating: Dict[str, Tuple[Tuple[int, ...], Optional[str]]] = {
            n: (p, None) for n, p in info.kernels.items() if p
        }
        if self.interprocedural:
            graph = callgraph.get(project)
            for name, (positions, label) in graph.donating_functions(
                module
            ).items():
                donating.setdefault(name, (positions, label))
        if not donating:
            return ()
        findings: List[Finding] = []
        for func in ast.walk(module.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._check_function(module, donating, func))
        # module-level statements can call kernels too
        findings.extend(
            self._check_statements(module, donating, {}, module.tree.body)
        )
        return findings

    def _check_function(self, module, donating, func):
        return self._check_statements(module, donating, {}, func.body)

    def _check_statements(self, module, donating, aliases, body):
        """Linear walk: track donating-kernel aliases, poison donated
        names, report loads of poisoned names, clear on rebind."""
        statements = statements_in_order(body)
        aliases = dict(aliases)
        poisoned: Dict[str, Tuple[str, int]] = {}  # name -> (kernel, line)
        findings: List[Finding] = []
        for stmt in statements:
            # loads first (x = f(x) reads before it writes)
            for name, line in _loaded_names_with_lines(stmt):
                hit = poisoned.get(name)
                if hit is not None:
                    kernel, donated_at = hit
                    findings.append(
                        Finding(
                            path=module.path,
                            line=line,
                            rule=self.id,
                            message=(
                                f"'{name}' was donated to {kernel} on line "
                                f"{donated_at} — its buffer may be reused "
                                "in place; use the call's results or "
                                "re-materialize before reading"
                            ),
                            data=(name, kernel),
                        )
                    )
                    del poisoned[name]  # one report per donation site
            # alias tracking (step = _x_donating if ok else _x)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if isinstance(target, ast.Name):
                    entry = _donating_alias(donating, stmt.value)
                    if entry:
                        aliases[target.id] = entry
                    elif target.id in aliases:
                        del aliases[target.id]
            # donation: any call to a donating kernel (or alias) in stmt
            calls = [
                sub
                for header in _header_nodes(stmt)
                for sub in ast.walk(header)
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
            ]
            for sub in calls:
                entry = donating.get(sub.func.id) or aliases.get(sub.func.id)
                if not entry:
                    continue
                positions, label = entry
                if any(isinstance(a, ast.Starred) for a in sub.args):
                    continue  # positions unknowable statically
                kernel = sub.func.id if label is None else f"{sub.func.id} ({label})"
                for pos in positions:
                    if pos < len(sub.args) and isinstance(sub.args[pos], ast.Name):
                        poisoned[sub.args[pos].id] = (kernel, sub.lineno)
            # rebinds clear the poison (after the call in the same stmt)
            for name in _stored_names(stmt):
                poisoned.pop(name, None)
        return findings
