"""spec-consistency: in_specs → body → out_specs must tell one story.

``shard_map``'s ``out_specs`` are a *claim*: this output is replicated /
sharded thus. jax checks the claim only as far as shapes go — a body
that returns a **per-shard partial sum** under ``out_specs=P()`` does
not error, it silently publishes shard 0's partial (or, with vma checks
off, whatever the backend picks) as if it were the global result. The
mirror bug is reducing a value that is *already* uniform along the
axis: ``psum`` of a replicated operand multiplies it by the shard count
— the classic double-counting that makes a loss exactly N× too large
and an N-device run "converge" to different coefficients than a
1-device run.

The interpreter (``analysis/spmd.py``) propagates the in_specs through
the body as variance sets, so this rule can flag both ends statically:

- **unreduced-output** — a return value still varies over mesh axes the
  out_spec says it does not have (declared replicated, never reduced);
- **double-reduce** — a reduction over an axis the operand is already
  uniform on (never sharded there, or already reduced once);
- **spec-arity** — ``in_specs`` entry count does not match the body's
  parameters (specs silently zip-truncate; the tail params get
  whatever jax defaults to).

Unknown specs / unresolvable values suppress findings — the engine
under-approximates, so every finding is worth reading.
"""

from __future__ import annotations

from typing import Iterable

from .. import spmd
from ..engine import Finding, Rule, register


@register
class SpecConsistencyRule(Rule):
    id = "spec-consistency"
    title = "shard_map specs inconsistent with the body's reductions"
    rationale = (
        "out_specs are a claim jax does not verify semantically: a "
        "per-shard partial returned under P() silently publishes one "
        "shard's partial as the global result, and a psum of an "
        "already-replicated operand multiplies by the shard count "
        "(double-counting) — both are silent numeric corruption, the "
        "exact class of bug the 2D-mesh work would otherwise have to "
        "debug from wrong coefficients. The abstract interpreter "
        "propagates in_specs through the body so both directions are "
        "caught at lint time."
    )
    example = "shard_map_over(mesh, (P(DATA_AXIS),), P(), fn=body)  # body never reduces"
    scope = ("flink_ml_tpu",)

    def check_project(self, project) -> Iterable[Finding]:
        interp = spmd.interpretation(project)
        for event in interp.of_kind("unreduced-output"):
            if not self.applies_to(event.path):
                continue
            axes = ", ".join(event.extra[0]) if event.extra else "?"
            site_line = event.extra[1] if len(event.extra) > 1 else "?"
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"return value of {event.detail}() still varies over "
                    f"axis ({axes}) but the out_specs at line {site_line} "
                    "declare it reduced/replicated there — the program "
                    "publishes a per-shard partial as the global result; "
                    "reduce it (all_reduce_sum / all_gather) before "
                    "returning, or declare the sharded layout"
                ),
                data=("unreduced-output", event.detail) + tuple(event.extra[:1]),
            )
        for event in interp.of_kind("double-reduce"):
            if not self.applies_to(event.path):
                continue
            axis = event.extra[0] if event.extra else "?"
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"{event.detail} over axis {axis!r} but the operand is "
                    "already uniform along that axis — the reduction "
                    "multiplies by the shard count (double-counting); drop "
                    "the redundant reduce or fix the PartitionSpec that "
                    "claimed the operand replicated"
                ),
                data=("double-reduce", event.detail, axis),
            )
        for event in interp.of_kind("spec-arity"):
            if not self.applies_to(event.path):
                continue
            n_specs, n_params = (event.extra + ("?", "?"))[:2]
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"in_specs carries {n_specs} spec(s) but {event.detail}() "
                    f"takes {n_params} parameter(s) — specs zip against "
                    "params positionally, so the mismatch silently mis-"
                    "shards the tail"
                ),
                data=("spec-arity", event.detail),
            )
