"""snapshot-commit: durable writes in ckpt/ must ride the atomic commit.

The checkpoint subsystem's whole correctness story is ONE invariant: a
reader never observes a torn file, because every durable publish is the
temp-file-then-`os.replace` unit of `ckpt/coordinator.atomic_commit` —
with the fault tick between payload and rename (so torn writes stay
fault-injectable) and the `flow.with_retries` wrapper around the whole
unit (so transient I/O retries re-run an unobservable sequence). The
multi-host protocol raises the stakes: a snapshot cut is now MANY files,
and a single raw `np.savez`/`json.dump`/`os.replace` sequence hand-rolled
next to the helper silently forfeits atomicity, retryability, AND the
chaos-harness coverage (no kill site inside it — the fault matrix can't
even see it).

The rule flags, in any module under a ``ckpt/`` directory:

- ``os.replace`` / ``os.rename`` calls,
- ``np.savez`` / ``np.save`` / ``np.savez_compressed`` calls,
- ``json.dump`` calls and write-mode builtin ``open(...)`` calls,

UNLESS the call is part of the sanctioned commit machinery:

- lexically inside the ``atomic_commit`` helper itself, or
- lexically inside an ``atomic_commit(...)`` CALL (the inline
  ``lambda tmp: np.savez(tmp, ...)`` payload idiom), or
- inside a function whose NAME is referenced within an
  ``atomic_commit(...)`` call in the same module (the named payload-
  writer idiom, e.g. ``_dump_json``).

Reads, deletes (`os.remove` — GC is not a commit) and writes outside
ckpt/ are not this rule's business. A deliberate exception takes a
``# tpulint: disable=snapshot-commit -- <why atomicity is not needed>``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name

COMMIT_HELPER = "atomic_commit"

#: numpy savers that produce durable payload files
_NP_WRITERS = ("savez", "save", "savez_compressed")


def _in_ckpt(path: str) -> bool:
    return "ckpt" in path.split("/")[:-1]


def _write_call_kind(node: ast.Call) -> str:
    """A short label when `node` is a durable-write call, else ''."""
    name = dotted_name(node.func)
    if name is None:
        return ""
    if name in ("os.replace", "os.rename"):
        return name
    root, _, rest = name.partition(".")
    if root in ("np", "numpy") and rest in _NP_WRITERS:
        return name
    if name == "json.dump":
        return name
    if name == "open":
        mode = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if isinstance(mode, str) and any(c in mode for c in "wax+"):
            return f"open(..., {mode!r})"
    return ""


def _sanctioned_nodes(module: SourceModule) -> Set[int]:
    """ids of AST nodes inside the commit machinery: the helper's own
    def, every `atomic_commit(...)` call subtree, and the defs of
    functions referenced inside those calls (named payload writers)."""
    sanctioned: Set[int] = set()
    payload_names: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == COMMIT_HELPER:
                for sub in ast.walk(node):
                    sanctioned.add(id(sub))
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None and name.split(".")[-1] == COMMIT_HELPER:
                for sub in ast.walk(node):
                    sanctioned.add(id(sub))
                    if isinstance(sub, ast.Name):
                        payload_names.add(sub.id)
    if payload_names:
        for node in ast.walk(module.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in payload_names
            ):
                for sub in ast.walk(node):
                    sanctioned.add(id(sub))
    return sanctioned


@register
class SnapshotCommitRule(Rule):
    id = "snapshot-commit"
    title = "durable write in ckpt/ outside the atomic commit helper"
    rationale = (
        "Every durable file publish in the checkpoint subsystem must be "
        "the coordinator's temp+os.replace atomic_commit unit: it is what "
        "keeps torn writes unobservable to readers, transient faults "
        "retryable (the whole unit re-runs), and the chaos harness able "
        "to kill mid-commit (the fault tick lives inside it). A raw "
        "multi-file write sequence beside it is an unprotected, "
        "un-chaos-tested commit path."
    )
    example = 'np.savez(target, **arrays); os.replace(tmp, target)  # in ckpt/'
    scope = ("flink_ml_tpu",)

    def check_module(self, project, module: SourceModule) -> Iterable[Finding]:
        if module.tree is None or not _in_ckpt(module.path):
            return ()
        sanctioned = _sanctioned_nodes(module)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or id(node) in sanctioned:
                continue
            kind = _write_call_kind(node)
            if not kind:
                continue
            findings.append(
                Finding(
                    path=module.path,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        f"{kind} writes a durable checkpoint file outside "
                        f"the {COMMIT_HELPER} temp+replace unit — torn "
                        "writes become observable, transient faults are "
                        "not retried as a unit, and the fault matrix has "
                        "no kill site inside this sequence; route it "
                        f"through coordinator.{COMMIT_HELPER}"
                    ),
                    data=("write", kind),
                )
            )
        return findings
