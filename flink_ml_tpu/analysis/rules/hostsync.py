"""host-sync-leak: implicit or unaccounted device→host synchronization.

The dispatch-bound bench verdict (wallMs 299 vs hostDispatchMs 297) means
a single stray device→host pull in a hot path stalls the whole pipeline
for a ~100ms tunnel round trip — and nothing in the profile says which
line did it. The sanctioned funnel is ``utils/packing.packed_device_get``
(one packed transfer, ``host_sync.*``/``readback.*`` accounted); this
rule flags the ways a sync leaks around it:

- ``np.asarray(x)`` / ``np.array(x)`` where ``x`` traces back to a
  device array (a jnp/lax call, a jitted kernel's result, memoized
  ``device_constants()``) — numpy silently issues a blocking
  device→host copy;
- ``float(x)`` / ``int(x)`` / ``bool(x)`` on such values — same sync,
  hidden in a cast;
- ``.item()`` — the idiomatic scalar pull, always a blocking sync;
- ``block_until_ready`` — a deliberate barrier, which is exactly why it
  must be either inside an accounted funnel or annotated with a
  suppression carrying its reason;
- **a device value passed to a helper that syncs it** — since v2 the
  rule consults the project call graph (``analysis/callgraph.py``): a
  *known* call resolves to the callee's bounded-depth summary, so an
  ``np.asarray`` buried two helpers deep is flagged at the top-level
  call site, with the full call chain and the sink's file:line in the
  finding.

Taint is tracked per function as source sets (device and/or parameter
origins); the interprocedural summaries fold parameter-sourced sinks
into the callers. *Unknown* calls still launder taint — the rule
under-approximates by design, so every finding is worth reading. The
resulting suppression set IS the library's audited census of host sync
points.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .. import callgraph
from ..engine import Finding, Rule, register
from ..source import SourceModule
from . import _jitindex

# backwards-compatible aliases (v1 exported these from here)
_META_ATTRS = callgraph.META_ATTRS
_HOST_SINKS = callgraph.HOST_SINKS

_DIRECT_MESSAGES = {
    "barrier": (
        "block_until_ready is a blocking device sync outside the "
        "accounted funnels — route the readback through "
        "packed_device_get, or suppress with the reason this "
        "barrier is deliberate"
    ),
    "item": (
        ".item() issues a blocking device->host scalar pull — "
        "batch it through packed_device_get (or keep the value "
        "on device)"
    ),
}


@register
class HostSyncLeakRule(Rule):
    id = "host-sync-leak"
    title = "implicit or unaccounted device->host synchronization"
    rationale = (
        "The train loop is host-dispatch-bound; one stray device->host "
        "pull stalls it for a full tunnel round trip and vanishes from "
        "hostSyncCount. Every sync must ride packed_device_get (packed, "
        "accounted) or carry a suppression stating why it is deliberate — "
        "the suppression set doubles as the library's host-sync census. "
        "Since v2 the taint is interprocedural: a pull laundered through "
        "helper functions is flagged at the call site with the chain."
    )
    example = "centers = np.asarray(dev_centroids)  # implicit D2H pull"
    scope = ("flink_ml_tpu",)
    # the funnel itself performs the one sanctioned transfer
    exclude = ("flink_ml_tpu/utils/packing.py",)
    #: consult callee summaries (False = tpulint v1 per-function recall,
    #: kept as the baseline the tier-1 superset test compares against)
    interprocedural = True

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        info = _jitindex.jit_index(project)[module.path]
        graph = callgraph.get(project) if self.interprocedural else None
        events: List[callgraph.SyncEvent] = []

        covered = set()
        if graph is not None:
            for decl in graph.decls_in(module.path).values():
                covered.add(id(decl.node))
                events.extend(graph.analyze(decl).events)

        def walk(body, params):
            walker = callgraph.TaintWalker(
                graph=graph, module=module, info=info, params=params
            )
            walker.run_block(body)
            events.extend(walker.events)

        # nested functions (and, without the call graph, every function)
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in covered:
                    continue
                params = {
                    a.arg: i
                    for i, a in enumerate(
                        list(node.args.posonlyargs) + list(node.args.args)
                    )
                }
                walk(node.body, params)
        # module level (rare, but kernels can be exercised at import)
        walk(module.tree.body, {})

        findings: List[Finding] = []
        suppressed_here = module.suppressions_for(self.id)
        for event in events:
            if callgraph.DEVICE in event.sources:
                findings.append(self._finding(module, event))
            elif (
                graph is not None
                and not event.funcs
                and event.kind in ("np-pull", "cast")
                and event.line in suppressed_here
            ):
                # parameter-sourced sink under a suppression: the callee
                # summary dropped it (documented deliberate sync) — emit
                # the census finding so --show-suppressed lists it and the
                # annotation cannot rot unused
                findings.append(
                    Finding(
                        path=module.path,
                        line=event.line,
                        rule=self.id,
                        message=(
                            f"{'np.' if event.kind == 'np-pull' else ''}"
                            f"{event.detail}"
                            f"{'' if event.kind == 'np-pull' else '()'} on a "
                            "function parameter is a blocking pull when "
                            "callers pass device values — deliberate here "
                            "(suppressed); callers inherit no finding"
                        ),
                        data=(f"{event.kind}-param", event.detail),
                    )
                )
        # nested scopes can be revisited — dedup on (line, message)
        seen = set()
        unique = []
        for f in findings:
            key = (f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        return unique

    def _finding(self, module: SourceModule, event) -> Finding:
        if event.funcs:
            chain = " -> ".join(event.funcs)
            sink = (
                f"np.{event.detail}" if event.kind == "np-pull" else f"{event.detail}()"
            )
            message = (
                f"device value passed to {event.funcs[0]}() is pulled to the "
                f"host by {sink} at {event.sink_path}:{event.sink_line} "
                f"(call chain: {chain}) — an implicit device->host sync "
                "laundered through helpers; route the readback through "
                "packed_device_get or keep the helper on device"
            )
            data = (f"{event.kind}-chain", event.detail) + tuple(event.funcs)
        elif event.kind in _DIRECT_MESSAGES:
            message = _DIRECT_MESSAGES[event.kind]
            data = (event.detail,)
        elif event.kind == "np-pull":
            message = (
                f"np.{event.detail} on a device value is an implicit device->host "
                "pull — route it through packed_device_get (accounted, "
                "packed) or keep the computation on the host branch"
            )
            data = ("np-pull", event.detail)
        else:  # cast
            message = (
                f"{event.detail}() on a device value is a hidden blocking sync — "
                "read it back through packed_device_get with the fit's "
                "packed result instead"
            )
            data = ("cast", event.detail)
        return Finding(
            path=module.path,
            line=event.line,
            rule=self.id,
            message=message,
            data=data,
        )
