"""host-sync-leak: implicit or unaccounted device→host synchronization.

The dispatch-bound bench verdict (wallMs 299 vs hostDispatchMs 297) means
a single stray device→host pull in a hot path stalls the whole pipeline
for a ~100ms tunnel round trip — and nothing in the profile says which
line did it. The sanctioned funnel is ``utils/packing.packed_device_get``
(one packed transfer, ``host_sync.*``/``readback.*`` accounted); this
rule flags the ways a sync leaks around it:

- ``np.asarray(x)`` / ``np.array(x)`` where ``x`` dataflow-locally traces
  back to a device array (a jnp/lax call, a jitted kernel's result,
  memoized ``device_constants()``) — numpy silently issues a blocking
  device→host copy;
- ``float(x)`` / ``int(x)`` / ``bool(x)`` on such values — same sync,
  hidden in a cast;
- ``.item()`` — the idiomatic scalar pull, always a blocking sync;
- ``block_until_ready`` — a deliberate barrier, which is exactly why it
  must be either inside an accounted funnel or annotated with a
  suppression carrying its reason.

Taint is tracked per function, linearly (assignments through jnp/lax
namespaces, known jit kernels and keyed-kernel factories, arithmetic on
tainted values, tuple unpacking); host-producing calls
(``packed_device_get``, ``jax.device_get``, ``np.asarray``) clear it.
Shape/dtype/ndim attribute reads are host metadata, not taint. The rule
under-approximates by design: unknown calls launder taint, so every
finding is worth reading — fix it through the funnel or suppress it with
the reason the sync is deliberate. The resulting suppression set IS the
library's audited census of host sync points.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name
from . import _astwalk, _jitindex

# attribute reads that return host metadata, not device payloads
_META_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "sharding", "itemsize"}

# call targets that return HOST values (clear taint)
_HOST_SINKS = {
    "packed_device_get",
    "device_get",  # jax.device_get
    "float",
    "int",
    "bool",
    "len",
    "str",
    "repr",
}


class _FunctionTaint(ast.NodeVisitor):
    """Linear taint pass over one function body."""

    def __init__(self, rule, module, info, findings):
        self.rule = rule
        self.module = module
        self.info = info
        self.findings = findings
        self.tainted: Set[str] = set()

    # -- taint evaluation ----------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            return self.call_returns_device(node)
        if isinstance(node, ast.Attribute):
            if node.attr in _META_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.is_tainted(node.value)
        return False

    def call_returns_device(self, call: ast.Call) -> bool:
        func = call.func
        name = dotted_name(func)
        if name is not None:
            base = name.split(".")[-1]
            if base in _HOST_SINKS:
                return False
            root = name.split(".")[0]
            if root in self.info.np_aliases:
                return False  # numpy returns host arrays
            if self.info.device_namespace_call(func):
                return True
            if name in self.info.kernels:
                return True
            # method producing the memoized device-constant dict
            if base == "device_constants":
                return True
        # keyed factory double-call: jit_find_closest(measure)(X, C)
        if isinstance(func, ast.Call):
            inner = dotted_name(func.func)
            if inner is not None and (
                inner in self.info.factories
                or inner in self.info.keyed_jit_names
            ):
                return True
            if self.info.is_jit_callable(func.func):
                return True  # jax.jit(f)(args) / lazy_jit(f)(args)
        # x.method() where x is tainted: device-array methods (astype,
        # reshape, sum, ...) stay on device
        if (
            isinstance(func, ast.Attribute)
            and func.attr not in _META_ATTRS
            and self.is_tainted(func.value)
        ):
            return True
        return False

    # -- statement handling --------------------------------------------------

    def assign(self, target: ast.AST, value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign(
                    elt.value if isinstance(elt, ast.Starred) else elt,
                    value_tainted,
                )

    def run_block(self, body) -> None:
        for stmt in body:
            self.run_statement(stmt)

    def run_statement(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope, analyzed on its own
        self.scan_expressions(stmt)
        if isinstance(stmt, ast.Assign):
            tainted = self.is_tainted(stmt.value)
            for target in stmt.targets:
                self.assign(target, tainted)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self.assign(stmt.target, self.is_tainted(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                if self.is_tainted(stmt.value) or self.is_tainted(stmt.target):
                    self.tainted.add(stmt.target.id)
        elif isinstance(stmt, ast.For):
            self.assign(stmt.target, self.is_tainted(stmt.iter))
            self.run_block(stmt.body)
            self.run_block(stmt.orelse)
            return
        elif isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self.assign(
                        item.optional_vars, self.is_tainted(item.context_expr)
                    )
            self.run_block(stmt.body)
            return
        for block in (
            getattr(stmt, "body", None),
            getattr(stmt, "orelse", None),
            getattr(stmt, "finalbody", None),
        ):
            if block and isinstance(block, list):
                self.run_block(block)
        for handler in getattr(stmt, "handlers", []) or []:
            self.run_block(handler.body)

    # -- finding generation --------------------------------------------------

    def scan_expressions(self, stmt: ast.stmt) -> None:
        # only the statement's own expressions: nested blocks are walked as
        # their own statements by run_block, AFTER the taint state caught up
        for header in _astwalk.header_nodes(stmt):
            for node in ast.walk(header):
                if isinstance(node, ast.Call):
                    self.check_call(node)

    def check_call(self, call: ast.Call) -> None:
        func = call.func
        name = dotted_name(func)

        # block_until_ready: barrier outside the accounted funnels
        if (isinstance(func, ast.Attribute) and func.attr == "block_until_ready") or (
            name is not None and name.split(".")[-1] == "block_until_ready"
        ):
            self.emit(
                call.lineno,
                "block_until_ready is a blocking device sync outside the "
                "accounted funnels — route the readback through "
                "packed_device_get, or suppress with the reason this "
                "barrier is deliberate",
                ("block_until_ready",),
            )
            return

        # .item(): always a scalar pull
        if isinstance(func, ast.Attribute) and func.attr == "item" and not call.args:
            self.emit(
                call.lineno,
                ".item() issues a blocking device->host scalar pull — "
                "batch it through packed_device_get (or keep the value "
                "on device)",
                ("item",),
            )
            return

        if name is None or not call.args:
            return
        root, _, rest = name.partition(".")
        arg = call.args[0]

        # np.asarray / np.array on a device value
        if (
            root in self.info.np_aliases
            and rest in ("asarray", "array", "ascontiguousarray")
            and self.is_tainted(arg)
        ):
            self.emit(
                call.lineno,
                f"np.{rest} on a device value is an implicit device->host "
                "pull — route it through packed_device_get (accounted, "
                "packed) or keep the computation on the host branch",
                ("np-pull", rest),
            )
            return

        # float()/int()/bool() casts on a device value
        if name in ("float", "int", "bool") and self.is_tainted(arg):
            self.emit(
                call.lineno,
                f"{name}() on a device value is a hidden blocking sync — "
                "read it back through packed_device_get with the fit's "
                "packed result instead",
                ("cast", name),
            )


    def emit(self, line: int, message: str, data: Tuple) -> None:
        self.findings.append(
            Finding(
                path=self.module.path,
                line=line,
                rule=self.rule.id,
                message=message,
                data=data,
            )
        )


@register
class HostSyncLeakRule(Rule):
    id = "host-sync-leak"
    title = "implicit or unaccounted device->host synchronization"
    rationale = (
        "The train loop is host-dispatch-bound; one stray device->host "
        "pull stalls it for a full tunnel round trip and vanishes from "
        "hostSyncCount. Every sync must ride packed_device_get (packed, "
        "accounted) or carry a suppression stating why it is deliberate — "
        "the suppression set doubles as the library's host-sync census."
    )
    example = "centers = np.asarray(dev_centroids)  # implicit D2H pull"
    scope = ("flink_ml_tpu",)
    # the funnel itself performs the one sanctioned transfer
    exclude = ("flink_ml_tpu/utils/packing.py",)

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        info = _jitindex.jit_index(project)[module.path]
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                tracker = _FunctionTaint(self, module, info, findings)
                tracker.run_block(node.body)
        # module level (rare, but kernels can be exercised at import)
        tracker = _FunctionTaint(self, module, info, findings)
        tracker.run_block(module.tree.body)
        # nested functions are revisited by the outer ast.walk — dedup
        seen = set()
        unique = []
        for f in findings:
            key = (f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        return unique
