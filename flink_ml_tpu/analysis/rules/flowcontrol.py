"""unbounded-queue: queues without a bound, threads outside the flow layer.

The flow-control sweep (flow.py, docs/flow_control.md) exists because four
hand-rolled bounded windows had quietly diverged — and the failure mode of
the NEXT hand-rolled one is worse: a `queue.Queue()` or
`collections.deque()` constructed without a bound grows until the host
falls over the moment its consumer is slower than its producer, and a raw
`threading.Thread` outside the sanctioned spawn points (`flow.pump` /
`flow.spawn`, plus the prefetch module built on them) is a worker whose
errors nothing routes back to a consumer — the silently-dead-producer
stall `flow.pump`'s close-with-error contract was built to kill. The rule
pins both hazards:

- **unbounded queue constructors** — `collections.deque(...)` with no
  ``maxlen=`` keyword, and `queue.Queue()` / `LifoQueue()` /
  `PriorityQueue()` / `SimpleQueue()` with no positive ``maxsize``
  (`SimpleQueue` cannot be bounded at all). Route producer/consumer
  hand-offs through `flow.BoundedChannel`, whose overload policy is an
  explicit decision (`block` / `shed_oldest` / `sample` / `reject`); a
  deque used as plain scratch storage takes a ``maxlen`` or a
  suppression-with-reason stating what bounds it.
- **raw thread spawns** — `threading.Thread(...)` anywhere outside
  `flow.py` / `parallel/prefetch.py`. Use `flow.pump` (iterable → channel
  with the close-with-error contract) or `flow.spawn`.

Suppression etiquette (docs/static_analysis.md): a deliberately unbounded
or logic-bounded structure carries
``# tpulint: disable=unbounded-queue -- <what bounds it>`` so the census
stays auditable.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List

from ..engine import Finding, Rule, register
from ..source import SourceModule

_QUEUE_CLASSES = ("Queue", "LifoQueue", "PriorityQueue", "SimpleQueue")


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Map local names to the dotted things they import: `collections`,
    `queue`, `threading` modules and their relevant members."""
    aliases: Dict[str, str] = {}
    interesting_modules = ("collections", "queue", "threading")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in interesting_modules:
                    aliases[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module in interesting_modules:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _call_target(node: ast.Call, aliases: Dict[str, str]) -> str:
    """The dotted import-resolved name a call constructs, or ''."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return aliases.get(fn.id, "")
    if (
        isinstance(fn, ast.Attribute)
        and isinstance(fn.value, ast.Name)
        and aliases.get(fn.value.id) in ("collections", "queue", "threading")
    ):
        return f"{aliases[fn.value.id]}.{fn.attr}"
    return ""


def _has_bounding_maxlen(node: ast.Call) -> bool:
    """deque(...) is bounded iff it passes a non-None maxlen (second
    positional or keyword)."""
    for kw in node.keywords:
        if kw.arg == "maxlen":
            return not (isinstance(kw.value, ast.Constant) and kw.value.value is None)
    return len(node.args) >= 2


def _has_bounding_maxsize(node: ast.Call) -> bool:
    """queue.Queue(...) is bounded iff maxsize is a non-zero, non-negative
    value (0 and negative mean infinite). A non-literal expression gets
    the benefit of the doubt."""
    value = None
    if node.args:
        value = node.args[0]
    for kw in node.keywords:
        if kw.arg == "maxsize":
            value = kw.value
    if value is None:
        return False
    if isinstance(value, ast.Constant) and isinstance(value.value, (int, float)):
        return value.value > 0
    if isinstance(value, ast.UnaryOp) and isinstance(value.op, ast.USub):
        return False  # negative literal: infinite by the queue contract
    return True  # dynamic bound: assume the caller computed one


@register
class UnboundedQueueRule(Rule):
    id = "unbounded-queue"
    title = "unbounded queue constructors and raw thread spawns"
    rationale = (
        "An unbounded queue is a memory leak with a trigger condition: "
        "the first time its consumer is slower than its producer it "
        "grows until the host dies — the overload case flow.BoundedChannel "
        "makes an explicit policy decision (block / shed_oldest / sample "
        "/ reject). A raw threading.Thread outside the flow layer is a "
        "worker whose failure nothing reports: the consumer blocks on a "
        "silently-dead producer. Route hand-offs through "
        "flow.BoundedChannel and spawns through flow.pump / flow.spawn, "
        "or bound the structure (deque maxlen, Queue maxsize) — or "
        "suppress WITH the reason that bounds it."
    )
    example = "pending = deque()  # use flow.BoundedChannel(depth) / deque(maxlen=n)"
    scope = ("flink_ml_tpu",)
    # flow.py IS the sanctioned implementation site for both hazards;
    # parallel/prefetch.py is its historical twin (the module the staging
    # windows grew in) and stays exempt per the flow-control contract
    exclude = ("flink_ml_tpu/flow.py", "flink_ml_tpu/parallel/prefetch.py")

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        aliases = _import_aliases(module.tree)
        if not aliases:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            target = _call_target(node, aliases)
            if target == "collections.deque" and not _has_bounding_maxlen(node):
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            "unbounded collections.deque() — grows without "
                            "limit once the consumer falls behind; use "
                            "flow.BoundedChannel (policy-explicit) or pass "
                            "maxlen="
                        ),
                        data=("deque",),
                    )
                )
            elif target == "queue.SimpleQueue" or (
                target.startswith("queue.")
                and target.split(".", 1)[1] in _QUEUE_CLASSES
                and not _has_bounding_maxsize(node)
            ):
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            f"unbounded {target}() — maxsize<=0 means grow-"
                            "until-OOM under overload; use flow.BoundedChannel "
                            "or pass a positive maxsize"
                        ),
                        data=("queue",),
                    )
                )
            elif target == "threading.Thread":
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            "raw threading.Thread outside flow.py — a worker "
                            "whose errors nothing routes to its consumer; "
                            "spawn through flow.pump (iterable→channel, "
                            "close-with-error) or flow.spawn"
                        ),
                        data=("thread",),
                    )
                )
        return findings
