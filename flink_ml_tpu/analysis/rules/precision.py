"""precision-determinism: narrowing casts and unsanctioned fold orders.

Two ways a distributed reduction quietly stops being the number the
math says:

1. **Implicit downcast before a reduce.** An ``astype`` to bf16/f16
   (or an 8-bit type) immediately upstream of a ``psum``-family
   collective makes every addend lose mantissa *before* the sum — at
   dim=1e6 the accumulated error is not noise, it is a different
   model. An f32 accumulator over f32 operands is fine (and
   ``astype(jnp.float32)`` on the two tol-check scalars in overlap.py
   is exactly that); it is the *narrowing* direction that corrupts.
   The fix is to reduce in the operand's dtype (or wider) and narrow
   the *result* if the wire format demands it.

2. **Reduction-order-sensitive folds outside the sanctioned ring.**
   The bit-exactness contract of the comm layer (`docs/performance.md`
   §7) holds because the two hand-rolled folds — the ring ppermute
   fold in ``parallel/collectives.py`` and its overlap-scheduled
   caller — fold arrivals in **replica order**, the same association
   the backend's own all-reduce uses. A manual loop elsewhere that
   accumulates permuted shards reassociates the sum: bit-identity
   silently becomes "close enough", which breaks every parity test the
   repo pins (chunked==monolithic, overlap==eager, kill→resume
   bit-identical). New ring schedules belong next to the existing one,
   where the replica-order discipline and its parity suite live.

Both checks ride the shared SPMD layer (``analysis/spmd.py``): the
interpreter tracks narrowing provenance through assignments into
collective operands inside shard_map bodies, and a module-level scan
catches permute-accumulate loops anywhere outside the sanctioned
modules.
"""

from __future__ import annotations

from typing import Iterable

from .. import spmd
from ..engine import Finding, Rule, register


@register
class PrecisionDeterminismRule(Rule):
    id = "precision-determinism"
    title = "narrowing cast before a reduction / unsanctioned fold order"
    rationale = (
        "An astype to bf16/f16 upstream of a psum makes every addend "
        "lose mantissa BEFORE the sum — at wide dims that is a different "
        "model, not noise; reduce in the operand dtype and narrow the "
        "result instead. And a manual loop accumulating permuted shards "
        "outside parallel/collectives.py reassociates the reduction, "
        "breaking the replica-order bit-exactness contract every parity "
        "suite in the repo pins (chunked==monolithic, overlap==eager, "
        "resume bit-identical)."
    )
    example = "total = all_reduce_sum(grad.astype(jnp.bfloat16), DATA_AXIS)"
    scope = ("flink_ml_tpu",)

    def check_project(self, project) -> Iterable[Finding]:
        interp = spmd.interpretation(project)
        for event in interp.of_kind("downcast-before-reduce"):
            if not self.applies_to(event.path):
                continue
            dtype = event.extra[0] if event.extra else "?"
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"operand of {event.detail} was narrowed to {dtype} "
                    "before the reduction — every addend loses mantissa "
                    "before the sum; reduce in the operand's dtype (or "
                    "wider) and cast the reduced result instead"
                ),
                data=("downcast", event.detail, dtype),
            )
        for event in interp.of_kind("order-fold"):
            if not self.applies_to(event.path):
                continue
            loop_line = event.extra[0] if event.extra else "?"
            yield Finding(
                path=event.path,
                line=event.line,
                rule=self.id,
                message=(
                    f"loop at line {loop_line} accumulates permuted shards "
                    "— a hand-rolled ring fold outside the sanctioned "
                    "replica-order implementation in parallel/"
                    "collectives.py; its association differs from psum, so "
                    "results are no longer bit-identical to the monolithic "
                    "collective (the contract docs/performance.md §7 "
                    "pins). Build on _reduce_bucket_ring or add the new "
                    "schedule beside it with the same replica-order fold"
                ),
                data=("order-fold", event.detail),
            )
