"""Cross-module index of jit kernels, module aliases, and donation info.

Several rules need the same syntactic facts about a module:

- which local names are bound to numpy / jax / jax.numpy / jax.lax,
- which local names are jitted kernels (``N = lazy_jit(f)`` /
  ``N = jax.jit(f)`` / ``@jax.jit``-decorated defs), which are keyed
  factories (``N = keyed_jit(make)``), and which of those kernels donate
  which positional arguments,
- which imported names resolve to kernels defined in sibling modules
  (e.g. ``from ..ops.distance import jit_find_closest``).

This module builds that index once per project (memoized via
``Project.index``) so the retrace, donation-after-use, and host-sync
rules stay small and agree with each other.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple

from ..source import SourceModule, dotted_name, resolve_relative_import

LAZYJIT_MODULE = "flink_ml_tpu.utils.lazyjit"

# dotted prefixes of jax namespaces whose calls produce device arrays
DEVICE_NAMESPACE_SUFFIXES = ("numpy", "nn", "lax", "random")


@dataclass
class ModuleJitInfo:
    path: str
    module_name: str
    np_aliases: Set[str] = field(default_factory=set)
    jax_aliases: Set[str] = field(default_factory=set)
    jnp_aliases: Set[str] = field(default_factory=set)  # jax.numpy / jax.nn / ...
    lax_aliases: Set[str] = field(default_factory=set)
    lazy_jit_names: Set[str] = field(default_factory=set)  # bound to lazy_jit
    keyed_jit_names: Set[str] = field(default_factory=set)  # bound to keyed_jit
    # kernel name -> donated positional argument indices (empty = borrows)
    kernels: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    factories: Set[str] = field(default_factory=set)  # keyed_jit factories
    # imported name -> (module dotted path, original name) for later linking
    imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def is_jit_callable(self, node: ast.AST) -> bool:
        """Does this expression denote a jit entry point (jax.jit or a
        lazyjit helper)?"""
        name = dotted_name(node)
        if name is None:
            return False
        root, _, rest = name.partition(".")
        if root in self.jax_aliases and rest == "jit":
            return True
        return name in self.lazy_jit_names or name in self.keyed_jit_names

    def device_namespace_call(self, func: ast.AST) -> bool:
        """Is ``func`` a call target in a device-array-producing jax
        namespace (jnp.*, lax.*, jax.nn.*, jax.numpy.*, jax.random.*)?"""
        name = dotted_name(func)
        if name is None:
            return False
        root, _, rest = name.partition(".")
        if not rest:
            return False
        if root in self.jnp_aliases or root in self.lax_aliases:
            return True
        if root in self.jax_aliases:
            first = rest.split(".")[0]
            return first in DEVICE_NAMESPACE_SUFFIXES
        return False


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            value = kw.value
            if isinstance(value, ast.Constant) and isinstance(value.value, int):
                return (value.value,)
            if isinstance(value, (ast.Tuple, ast.List)):
                out = []
                for elt in value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        out.append(elt.value)
                return tuple(out)
    return ()


def _jit_call_kind(info: ModuleJitInfo, node: ast.AST) -> Optional[str]:
    """'kernel' / 'factory' if ``node`` is a jit-wrapper construction."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        # functools.partial(jax.jit, ...) used as a decorator
        return None
    root, _, rest = name.partition(".")
    if root in info.jax_aliases and rest == "jit":
        return "kernel"
    if name in info.lazy_jit_names:
        return "kernel"
    if name in info.keyed_jit_names:
        return "factory"
    return None


def _partial_jit_call(info: ModuleJitInfo, node: ast.AST) -> Optional[ast.Call]:
    """``partial(jax.jit, ...)`` / ``partial(lazy_jit, ...)`` -> the Call."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name not in ("partial", "functools.partial"):
        return None
    if node.args and info.is_jit_callable(node.args[0]):
        return node
    return None


def build_module_info(module: SourceModule) -> ModuleJitInfo:
    info = ModuleJitInfo(path=module.path, module_name=module.module_name)
    if module.tree is None:
        return info

    # pass 1: imports / aliases
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                if alias.name == "numpy":
                    info.np_aliases.add(bound)
                elif alias.name == "jax":
                    info.jax_aliases.add(bound)
                elif alias.name == "jax.numpy" and alias.asname:
                    info.jnp_aliases.add(alias.asname)
                elif alias.name == "jax.lax" and alias.asname:
                    info.lax_aliases.add(alias.asname)
        elif isinstance(node, ast.ImportFrom):
            target = resolve_relative_import(
                module.module_name, node, module.is_package
            )
            if target is None:
                continue
            for alias in node.names:
                bound = alias.asname or alias.name
                if target == "jax":
                    if alias.name == "numpy":
                        info.jnp_aliases.add(bound)
                    elif alias.name == "lax":
                        info.lax_aliases.add(bound)
                elif target == "jax.numpy":
                    info.jnp_aliases.add(bound)  # symbol import; treated as ns
                elif target == LAZYJIT_MODULE or target.endswith("utils.lazyjit"):
                    if alias.name == "lazy_jit":
                        info.lazy_jit_names.add(bound)
                    elif alias.name == "keyed_jit":
                        info.keyed_jit_names.add(bound)
                info.imports[bound] = (target, alias.name)

    # pass 2: module-level kernel bindings and jit-decorated defs
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            kind = _jit_call_kind(info, node.value)
            if kind == "kernel":
                info.kernels[target.id] = _donate_positions(node.value)
            elif kind == "factory":
                info.factories.add(target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if info.is_jit_callable(dec):
                    info.kernels.setdefault(node.name, ())
                    break
                partial_call = _partial_jit_call(info, dec)
                if partial_call is not None:
                    info.kernels[node.name] = _donate_positions(partial_call)
                    break
    return info


def build_index(project) -> Dict[str, ModuleJitInfo]:
    """path -> ModuleJitInfo with imported kernels linked across modules."""
    by_path: Dict[str, ModuleJitInfo] = {}
    by_module: Dict[str, ModuleJitInfo] = {}
    for module in project.modules:
        info = build_module_info(module)
        by_path[module.path] = info
        if module.module_name:
            by_module[module.module_name] = info
    # link imported kernels/factories (one hop is enough for this tree)
    for info in by_path.values():
        for bound, (target_module, original) in info.imports.items():
            target = by_module.get(target_module)
            if target is None:
                continue
            if original in target.kernels and bound not in info.kernels:
                info.kernels[bound] = target.kernels[original]
            if original in target.factories:
                info.factories.add(bound)
    return by_path


def jit_index(project) -> Dict[str, ModuleJitInfo]:
    return project.index("jitindex", build_index)
