"""lock-order: static lock-acquisition-order analysis (deadlock cycles).

PR 8 gave the library a real multithreaded substrate — the flow pump and
spawn workers, the serving dispatch loop, the prefetch stager, the epoch
cache — and Spark-era experience (PAPERS.md) says coordination stalls,
not FLOPs, dominate distributed-ML wall time. A lock inversion between
two of those threads is silent on every test run that doesn't hit the
exact interleaving, then deadlocks production. This rule holds the
ordering invariant **statically**:

- every ``threading.Lock`` / ``RLock`` / ``Condition`` creation in the
  package becomes a lock *node* — module-level ``_lock = Lock()`` by
  name, ``self._cv = Condition()`` by ``Class.attr`` (all instances of a
  class share the node: a consistent class-level order is exactly the
  discipline that keeps multi-instance locking safe);
- every function is walked linearly tracking the *held set*: ``with
  lock:`` blocks, explicit ``acquire()``/``release()`` pairs, and —
  via the project call graph (`analysis/callgraph.py`) plus light local
  type tracking (``ch = BoundedChannel(...)`` → ``ch.put(...)``) —
  locks acquired transitively inside calls made while holding;
- each "holding A, acquire B" observation is an edge A→B in the static
  lock-acquisition graph; a **cycle** is a finding (the ABBA deadlock
  shape), as is re-acquiring a non-reentrant ``Lock`` while already
  holding it (self-deadlock; RLock/Condition are reentrant and exempt).

The runtime half of the contract is ``analysis/sanitizer.py``: the
``FLINK_ML_TPU_SANITIZE=1`` recorder observes the *actual* cross-thread
acquisition DAG during tests and fails on cycles at process exit — the
static rule catches the inversion before it runs, the sanitizer catches
the lock the static pass could not see.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .. import callgraph
from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")
_REENTRANT = ("RLock", "Condition")


@dataclass(frozen=True)
class LockNode:
    node_id: str  # e.g. "flink_ml_tpu.flow.BoundedChannel._cv"
    kind: str  # Lock | RLock | Condition
    path: str
    line: int


@dataclass
class _EdgeSite:
    path: str
    line: int
    via: str  # "" for a direct nested with, else the callee qualname


@dataclass
class _ModuleLocks:
    threading_aliases: Set[str] = field(default_factory=set)  # `import threading as t`
    factory_aliases: Dict[str, str] = field(default_factory=dict)  # `from threading import Lock as L`
    module_locks: Dict[str, LockNode] = field(default_factory=dict)
    class_locks: Dict[str, Dict[str, LockNode]] = field(default_factory=dict)


def _lock_factory_kind(call: ast.AST, locks: _ModuleLocks) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when ``call`` constructs one."""
    if not isinstance(call, ast.Call):
        return None
    name = dotted_name(call.func)
    if name is None:
        return None
    root, _, rest = name.partition(".")
    if root in locks.threading_aliases and rest in _LOCK_FACTORIES:
        return rest
    if not rest and name in locks.factory_aliases:
        return locks.factory_aliases[name]
    return None


def _collect_module_locks(module: SourceModule) -> _ModuleLocks:
    locks = _ModuleLocks()
    if module.tree is None:
        return locks
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "threading":
                    locks.threading_aliases.add(a.asname or "threading")
        elif isinstance(node, ast.ImportFrom) and node.module == "threading":
            for a in node.names:
                if a.name in _LOCK_FACTORIES:
                    locks.factory_aliases[a.asname or a.name] = a.name

    def declare(name: str, cls: Optional[str], call: ast.Call, kind: str) -> None:
        # Condition(existing_lock) shares the wrapped lock's node — and
        # its (non-)reentrancy
        if kind == "Condition" and call.args:
            wrapped = _resolve_static_lock(call.args[0], locks, cls)
            if wrapped is not None:
                target = locks.class_locks.setdefault(cls, {}) if cls else locks.module_locks
                target[name] = wrapped
                return
        qual = f"{module.module_name}.{cls}.{name}" if cls else f"{module.module_name}.{name}"
        node_obj = LockNode(node_id=qual, kind=kind, path=module.path, line=call.lineno)
        if cls:
            locks.class_locks.setdefault(cls, {})[name] = node_obj
        else:
            locks.module_locks[name] = node_obj

    # module-level and class-level assignments; self.attr = ... in methods
    for top in module.tree.body:
        if isinstance(top, ast.Assign) and len(top.targets) == 1:
            target = top.targets[0]
            kind = _lock_factory_kind(top.value, locks)
            if kind and isinstance(target, ast.Name):
                declare(target.id, None, top.value, kind)
        elif isinstance(top, ast.ClassDef):
            for item in ast.walk(top):
                if not isinstance(item, ast.Assign) or len(item.targets) != 1:
                    continue
                kind = _lock_factory_kind(item.value, locks)
                if not kind:
                    continue
                target = item.targets[0]
                if isinstance(target, ast.Name):  # class attribute
                    declare(target.id, top.name, item.value, kind)
                elif (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    declare(target.attr, top.name, item.value, kind)
    return locks


def _resolve_static_lock(
    expr: ast.AST, locks: _ModuleLocks, current_class: Optional[str]
) -> Optional[LockNode]:
    """A lock expression (`_lock`, `self._cv`) to its node, else None."""
    if isinstance(expr, ast.Name):
        return locks.module_locks.get(expr.id)
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id in ("self", "cls")
        and current_class
    ):
        return locks.class_locks.get(current_class, {}).get(expr.attr)
    return None


class _FunctionLockWalker:
    """Linear walk of one function: records lock-order edges and the set
    of locks the function may acquire (for transitive call edges)."""

    def __init__(self, analysis: "_ProjectLockAnalysis", decl, module, locks):
        self.analysis = analysis
        self.decl = decl
        self.module = module
        self.locks = locks
        self.current_class = decl.qualname.split(".")[0] if decl.is_method else None
        self.acquired: Set[LockNode] = set()
        self.local_aliases: Dict[str, LockNode] = {}
        self.local_types: Dict[str, Tuple[str, str]] = {}  # name -> (path, class)

    # -- resolution ----------------------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[LockNode]:
        if isinstance(expr, ast.Name) and expr.id in self.local_aliases:
            return self.local_aliases[expr.id]
        return _resolve_static_lock(expr, self.locks, self.current_class)

    def _constructed_type(self, value: ast.AST) -> Optional[Tuple[str, str]]:
        """(module_path, ClassName) when ``value`` constructs a class the
        project declares (local or one-hop imported, incl. `flow.X(...)`)."""
        if not isinstance(value, ast.Call):
            return None
        graph = self.analysis.graph
        func = value.func
        if isinstance(func, ast.Name):
            name = func.id
            if self.analysis.has_class(self.module.path, name):
                return (self.module.path, name)
            info = graph.jitindex.get(self.module.path)
            if info is not None and name in info.imports:
                target_module, original = info.imports[name]
                target_path = graph.module_paths.get(target_module)
                if target_path and self.analysis.has_class(target_path, original):
                    return (target_path, original)
        elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            info = graph.jitindex.get(self.module.path)
            if info is not None and func.value.id in info.imports:
                target_module, original = info.imports[func.value.id]
                target_path = graph.module_paths.get(f"{target_module}.{original}")
                if target_path and self.analysis.has_class(target_path, func.attr):
                    return (target_path, func.attr)
        return None

    def _callee_acquires(self, call: ast.Call) -> Tuple[Set[LockNode], str]:
        """Locks a call may acquire (transitively), with a label."""
        graph = self.analysis.graph
        resolved = graph.resolve(self.module, call.func, self.current_class)
        if resolved is not None:
            decl, _ = resolved
            return self.analysis.acquires(decl), decl.qualname
        # typed local: ch.put(...) where ch = BoundedChannel(...)
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            typed = self.local_types.get(func.value.id)
            if typed is not None:
                path, cls = typed
                decl = graph.decls_in(path).get(f"{cls}.{func.attr}")
                if decl is not None:
                    return self.analysis.acquires(decl), decl.qualname
        return set(), ""

    # -- the walk ------------------------------------------------------------
    def run(self) -> Set[LockNode]:
        self._block(self.decl.node.body, [])
        return self.acquired

    def _note_acquire(self, node: LockNode, held: List[LockNode], line: int, via: str) -> None:
        self.acquired.add(node)
        for holder in held:
            self.analysis.add_edge(
                holder, node, _EdgeSite(path=self.module.path, line=line, via=via)
            )

    def _scan_calls(self, stmt: ast.stmt, held: List[LockNode]) -> None:
        from . import _astwalk

        for header in _astwalk.header_nodes(stmt):
            for node in ast.walk(header):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) and func.attr in (
                    "wait",
                    "notify",
                    "notify_all",
                    "locked",
                ):
                    continue  # condition-variable ops on an already-held lock
                if isinstance(func, ast.Attribute) and func.attr in ("acquire", "release"):
                    continue  # handled linearly by _block
                targets, via = self._callee_acquires(node)
                for target in sorted(targets, key=lambda n: n.node_id):
                    self._note_acquire(target, held, node.lineno, via)

    def _block(self, body: Sequence[ast.stmt], held: List[LockNode]) -> None:
        held = list(held)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # separate scope
            # explicit acquire()/release() pairs, tracked linearly
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute) and call.func.attr in (
                    "acquire",
                    "release",
                ):
                    node = self._lock_of(call.func.value)
                    if node is not None:
                        if call.func.attr == "acquire":
                            self._note_acquire(node, held, call.lineno, "")
                            held.append(node)
                        elif node in held:
                            held.remove(node)
                        continue
            self._scan_calls(stmt, held)
            # alias / type tracking
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if isinstance(target, ast.Name):
                    alias = self._lock_of(stmt.value)
                    if alias is not None:
                        self.local_aliases[target.id] = alias
                    else:
                        self.local_aliases.pop(target.id, None)
                        typed = self._constructed_type(stmt.value)
                        if typed is not None:
                            self.local_types[target.id] = typed
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered: List[LockNode] = []
                for item in stmt.items:
                    node = self._lock_of(item.context_expr)
                    if node is not None:
                        self._note_acquire(node, held, stmt.lineno, "")
                        entered.append(node)
                self._block(stmt.body, held + entered)
                continue
            for block in (
                getattr(stmt, "body", None),
                getattr(stmt, "orelse", None),
                getattr(stmt, "finalbody", None),
            ):
                if block and isinstance(block, list):
                    self._block(block, held)
            for handler in getattr(stmt, "handlers", []) or []:
                self._block(handler.body, held)


class _ProjectLockAnalysis:
    def __init__(self, project, scope_paths: Sequence[str]):
        self.project = project
        self.graph = callgraph.get(project)
        self.module_locks: Dict[str, _ModuleLocks] = {}
        self.edges: Dict[LockNode, Dict[LockNode, List[_EdgeSite]]] = {}
        self._acquires: Dict[Tuple[str, str], Set[LockNode]] = {}
        self._in_progress: Set[Tuple[str, str]] = set()
        self._classes: Dict[str, Set[str]] = {}
        for module in project.modules:
            self.module_locks[module.path] = _collect_module_locks(module)
            classes: Set[str] = set()
            if module.tree is not None:
                for top in module.tree.body:
                    if isinstance(top, ast.ClassDef):
                        classes.add(top.name)
            self._classes[module.path] = classes
        # drive the edge collection from every function in scope
        for module in project.modules:
            if not any(
                module.path == p or module.path.startswith(p.rstrip("/") + "/")
                for p in scope_paths
            ):
                continue
            for decl in self.graph.decls_in(module.path).values():
                self.acquires(decl)

    def has_class(self, path: str, name: str) -> bool:
        return name in self._classes.get(path, set())

    def add_edge(self, holder: LockNode, target: LockNode, site: _EdgeSite) -> None:
        self.edges.setdefault(holder, {}).setdefault(target, []).append(site)

    def acquires(self, decl) -> Set[LockNode]:
        """Locks ``decl`` may acquire, transitively; memoized and
        cycle-guarded (recursion contributes the empty set)."""
        key = decl.key
        if key in self._acquires:
            return self._acquires[key]
        if key in self._in_progress:
            return set()
        self._in_progress.add(key)
        try:
            module = self.project.module_at(decl.path)
            locks = self.module_locks.get(decl.path, _ModuleLocks())
            walker = _FunctionLockWalker(self, decl, module, locks)
            acquired = walker.run()
        finally:
            self._in_progress.discard(key)
        self._acquires[key] = acquired
        return acquired

    # -- cycle detection -----------------------------------------------------
    def cycles(self) -> List[List[LockNode]]:
        """Elementary cycles worth reporting: one representative per
        strongly-connected component of size > 1, plus non-reentrant
        self-loops."""
        out: List[List[LockNode]] = []
        nodes = sorted(self.edges, key=lambda n: n.node_id)
        for node in nodes:
            sites = self.edges.get(node, {}).get(node)
            if sites and node.kind not in _REENTRANT:
                out.append([node])
        # DFS-based cycle search over the (small) lock graph
        seen_cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: LockNode, current: LockNode, path: List[LockNode]) -> None:
            for nxt in sorted(self.edges.get(current, {}), key=lambda n: n.node_id):
                if nxt == start and len(path) > 1:
                    # canonical rotation for dedup
                    ids = [n.node_id for n in path]
                    pivot = ids.index(min(ids))
                    key = tuple(ids[pivot:] + ids[:pivot])
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(list(path))
                elif nxt not in path and nxt.node_id > start.node_id:
                    # only walk "later" nodes: each cycle found once, from
                    # its smallest member
                    dfs(start, nxt, path + [nxt])

        for node in nodes:
            dfs(node, node, [node])
        return out


@register
class LockOrderRule(Rule):
    id = "lock-order"
    title = "lock-acquisition-order cycles (static deadlock detection)"
    rationale = (
        "Two threads taking the same pair of locks in opposite orders "
        "deadlock on the first adverse interleaving — which no CPU test "
        "schedule may ever produce, and production will. The rule builds "
        "the static lock-acquisition graph over every threading.Lock/"
        "RLock/Condition in the package (with-blocks, acquire/release "
        "pairs, and locks taken inside calls made while holding, resolved "
        "through the project call graph); a cycle, or a re-acquire of a "
        "non-reentrant Lock, is a finding. Acquire locks in one global "
        "order, or split the critical section so no call is made while "
        "holding. The runtime twin is the FLINK_ML_TPU_SANITIZE=1 "
        "recorder (analysis/sanitizer.py)."
    )
    example = (
        "with self._a:\n"
        "    with self._b: ...   # thread 1: a -> b\n"
        "with self._b:\n"
        "    with self._a: ...   # thread 2: b -> a  -> cycle finding"
    )
    scope = ("flink_ml_tpu",)

    def check_project(self, project) -> Iterable[Finding]:
        analysis = _ProjectLockAnalysis(project, self.scope)
        findings: List[Finding] = []
        for cycle in analysis.cycles():
            if len(cycle) == 1:
                node = cycle[0]
                site = sorted(
                    analysis.edges[node][node], key=lambda s: (s.path, s.line)
                )[0]
                via = f" (via {site.via})" if site.via else ""
                findings.append(
                    Finding(
                        path=site.path,
                        line=site.line,
                        rule=self.id,
                        message=(
                            f"non-reentrant lock {node.node_id} ({node.kind}) "
                            f"re-acquired while already held{via} — "
                            "self-deadlock; use an RLock or restructure the "
                            "critical section"
                        ),
                        data=("self-deadlock", node.node_id),
                    )
                )
                continue
            # describe every edge of the cycle, anchor at the first site
            legs = []
            anchor: Optional[_EdgeSite] = None
            for i, node in enumerate(cycle):
                nxt = cycle[(i + 1) % len(cycle)]
                site = sorted(
                    analysis.edges[node][nxt], key=lambda s: (s.path, s.line)
                )[0]
                via = f" via {site.via}" if site.via else ""
                legs.append(
                    f"{node.node_id} -> {nxt.node_id} at {site.path}:{site.line}{via}"
                )
                if anchor is None or (site.path, site.line) < (anchor.path, anchor.line):
                    anchor = site
            order = " -> ".join(n.node_id for n in cycle + [cycle[0]])
            findings.append(
                Finding(
                    path=anchor.path,
                    line=anchor.line,
                    rule=self.id,
                    message=(
                        f"lock-order cycle {order}: "
                        + "; ".join(legs)
                        + " — two threads interleaving these acquisitions "
                        "deadlock; impose one global acquisition order"
                    ),
                    data=("cycle",) + tuple(n.node_id for n in cycle),
                )
            )
        return sorted(findings, key=lambda f: (f.path, f.line, f.message))
