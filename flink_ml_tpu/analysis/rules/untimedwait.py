"""untimed-wait: indefinite blocking calls outside the flow layer.

The elastic supervisor (parallel/supervisor.py) exists because a blocked
collective is invisible to everything except a deadline — and the same
failure shape hides in plain host code: a `Condition.wait()`,
`Event.wait()`, `Thread.join()` or queue/channel `.get()` WITHOUT a
timeout is a thread betting its liveness on another thread it cannot
observe. When that peer dies (the silently-dead-producer stall
`flow.pump`'s close-with-error contract kills) or wedges, the waiter
hangs forever, no counter moves, and the only recovery is a human with a
stack dump. `flow.py` is the one sanctioned home for indefinite waits —
its channel protocol pairs every wait with a close/cancel wake-up — so
everywhere else a blocking call must carry a timeout (loop on it if the
wait is legitimately long) or a suppression stating what guarantees the
wake-up.

Flagged:

- ``x.wait()`` / ``x.wait(timeout=None)`` — Condition/Event waits with
  no deadline;
- ``x.join()`` with no timeout — a Thread join that outlives a wedged
  worker forever (``", ".join(parts)`` takes an argument and is never
  flagged);
- ``x.get()`` with no arguments when ``x`` is queue-like: assigned from
  a ``BoundedChannel(...)`` / ``queue.Queue(...)``-family constructor in
  this module, or named like one (``*queue``, ``*channel``, ``*window``,
  ``*_q``). Dict/contextvar ``.get`` always carries an argument or a
  non-queue receiver and stays quiet.

Suppression etiquette (docs/static_analysis.md): a wait whose wake-up is
structurally guaranteed carries
``# tpulint: disable=untimed-wait -- <what guarantees the wake-up>``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from ..engine import Finding, Rule, register
from ..source import SourceModule

#: Receiver names that read as queues even without a visible constructor.
_QUEUEISH_NAME = re.compile(r"(queue|channel|chan|window)$|_q$|^q$", re.I)

#: Constructors whose results are queue-like (the `.get()` heuristic).
_QUEUE_CONSTRUCTORS = (
    "BoundedChannel",
    "Queue",
    "LifoQueue",
    "PriorityQueue",
    "SimpleQueue",
)


def _timeout_given(node: ast.Call) -> bool:
    """Does this call pass any deadline? A positional arg counts (wait's
    and join's first parameter IS the timeout); `timeout=None` does not."""
    for kw in node.keywords:
        if kw.arg == "timeout":
            return not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            )
    return bool(node.args)


def _terminal_name(expr: ast.expr) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _queueish_targets(tree: ast.AST) -> Set[str]:
    """Names (locals AND self-attributes) assigned from a queue-like
    constructor anywhere in the module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        ctor = _terminal_name(node.value.func)
        if ctor not in _QUEUE_CONSTRUCTORS:
            continue
        for target in node.targets:
            name = _terminal_name(target)
            if name:
                out.add(name)
    return out


@register
class UntimedWaitRule(Rule):
    id = "untimed-wait"
    title = "indefinite blocking calls outside the flow layer"
    rationale = (
        "A wait()/join()/get() without a timeout bets a thread's "
        "liveness on a peer it cannot observe: when the peer dies or "
        "wedges, the waiter hangs forever and no counter moves — the "
        "failure shape the elastic supervisor's hang watchdog exists "
        "to catch at the fit level. flow.py is the sanctioned home for "
        "indefinite waits (its channel protocol pairs every wait with "
        "a close/cancel wake-up); everywhere else, pass a timeout and "
        "loop, or suppress WITH the reason that guarantees the wake-up."
    )
    example = "done.wait()  # use done.wait(timeout) in a loop"
    scope = ("flink_ml_tpu",)
    exclude = ("flink_ml_tpu/flow.py",)

    def check_module(
        self, project, module: SourceModule
    ) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        queueish = _queueish_targets(module.tree)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            meth = node.func.attr
            if meth == "wait":
                if not _timeout_given(node):
                    findings.append(
                        Finding(
                            path=module.path,
                            line=node.lineno,
                            rule=self.id,
                            message=(
                                "untimed .wait() — blocks forever if the "
                                "notifier dies; pass a timeout and loop"
                            ),
                            data=("wait",),
                        )
                    )
            elif meth == "join":
                if not node.args and not _timeout_given(node):
                    findings.append(
                        Finding(
                            path=module.path,
                            line=node.lineno,
                            rule=self.id,
                            message=(
                                "untimed .join() — outlives a wedged worker "
                                "forever; pass join(timeout=...) and check "
                                "is_alive()"
                            ),
                            data=("join",),
                        )
                    )
            elif meth == "get" and not node.args and not node.keywords:
                recv = _terminal_name(node.func.value)
                if recv in queueish or _QUEUEISH_NAME.search(recv or ""):
                    findings.append(
                        Finding(
                            path=module.path,
                            line=node.lineno,
                            rule=self.id,
                            message=(
                                f"untimed {recv}.get() on a queue/channel — "
                                "blocks forever on a dead producer; pass "
                                "get(timeout=...) or prove non-blocking and "
                                "suppress with the reason"
                            ),
                            data=("get",),
                        )
                    )
        return findings
