"""serve-path-trace: the statically-checked no-compile serving SLA.

The AOT program bank (compilebank.py, docs/performance.md §12) promises
that a warmed serving process never traces or compiles on the request
path: every kernel the dispatch path can reach must route through a
bank-consulting funnel (``utils/lazyjit.py`` or ``compilebank.py``), so
that a bank hit is a warm-loaded executable call and the
``aotColdStart.serveTraceCount == 0`` CI pin holds by construction, not
by luck.

This rule walks the v2 call graph from the serving dispatch roots
(``MicroBatchServer`` and ``serve_stream``) and flags, in any reachable
function outside the sanctioned funnel modules:

- **raw ``jax.jit``** — a trace site the bank cannot see. The
  ``FusedSegment`` bank-off fallback is the one legitimate case and
  carries a suppression-with-reason (the census entry the acceptance
  criteria allow).
- **``lazy_jit``/``keyed_jit`` wrapper construction inside a reachable
  function body** — module-level wrappers are built at import time and
  consult the bank per call, but a wrapper constructed *on* the dispatch
  path traces on its first call mid-request, busting the SLA.

Reachability is an over-approximation on the serving surface: direct
resolution (module-level calls, one-hop imports, ``self.`` methods) via
``callgraph.CallGraph.resolve``, plus class-hierarchy lifting for
attribute calls — ``x.m(...)`` reaches every method named ``m`` declared
in the serving-path module set below. Over-approximate reachability +
exact trace-site matching keeps the rule sound for the SLA: a real trace
site on the path cannot hide behind an unresolvable receiver.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .. import callgraph
from ..engine import Finding, Rule, register
from . import _jitindex

#: the dispatch-path entry points the SLA protects
ROOTS = (
    ("flink_ml_tpu/serving.py", "MicroBatchServer."),
    ("flink_ml_tpu/serving.py", "serve_stream"),
)

#: modules whose classes participate in attribute-call (CHA) lifting —
#: the serving dispatch surface
CHA_MODULES = (
    "flink_ml_tpu/serving.py",
    "flink_ml_tpu/pipeline.py",
    "flink_ml_tpu/table.py",
    "flink_ml_tpu/api.py",
    "flink_ml_tpu/lifecycle.py",
    "flink_ml_tpu/data/modelstore.py",
    "flink_ml_tpu/parallel/prefetch.py",
    "flink_ml_tpu/utils/packing.py",
)

#: the bank-consulting funnels: trace sites INSIDE these are the
#: SLA's implementation, not violations of it
SANCTIONED = (
    "flink_ml_tpu/utils/lazyjit.py",
    "flink_ml_tpu/compilebank.py",
)


@register
class ServePathTraceRule(Rule):
    id = "serve-path-trace"
    title = "trace site reachable from the serving dispatch path"
    rationale = (
        "The no-compile serving SLA (docs/performance.md §12) requires "
        "every kernel reachable from MicroBatchServer's dispatch path to "
        "route through the bank-consulting funnels (utils/lazyjit.py, "
        "compilebank.py). A raw jax.jit or an on-path wrapper "
        "construction is a trace site the AOT program bank cannot "
        "satisfy — the first request that touches it traces and "
        "compiles mid-flight, which is exactly the dishonest-p999 "
        "cold start the bank exists to kill."
    )
    example = "self._jit = jax.jit(self._run)  # reachable from _dispatch"
    scope = ("flink_ml_tpu",)
    exclude = SANCTIONED

    def check_project(self, project) -> Iterable[Finding]:
        graph = callgraph.get(project)
        jitindex = _jitindex.jit_index(project)
        cha = _cha_index(graph)
        reachable = _reachable(project, graph, cha)
        findings: List[Finding] = []
        for (path, qualname), chain in sorted(reachable.items()):
            if any(path == s for s in SANCTIONED):
                continue
            decl = graph.by_module.get(path, {}).get(qualname)
            module = project.module_at(path)
            if decl is None or module is None:
                continue
            info = jitindex.get(path)
            findings.extend(
                self._trace_sites(module, info, decl, chain)
            )
        return findings

    def _trace_sites(self, module, info, decl, chain: str) -> List[Finding]:
        findings: List[Finding] = []
        via = f" (reached via {chain})" if chain else ""
        for node in ast.walk(decl.node):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id in info.jax_aliases
            ):
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=(
                            f"raw jax.jit in {decl.qualname} is reachable "
                            "from the serving dispatch path but not proven "
                            "bank-resolvable — route through the "
                            "lazyjit/compilebank funnels or suppress with "
                            f"a reason{via}"
                        ),
                        data=("raw-jit", decl.qualname),
                    )
                )
            elif isinstance(node, ast.Call):
                name = callgraph.dotted_name(node.func)
                if name is not None and (
                    name in info.lazy_jit_names or name in info.keyed_jit_names
                ):
                    findings.append(
                        Finding(
                            path=module.path,
                            line=node.lineno,
                            rule=self.id,
                            message=(
                                f"{name} wrapper constructed inside "
                                f"{decl.qualname} on the serving dispatch "
                                "path — its first call traces mid-request; "
                                "hoist the wrapper to module scope so the "
                                f"bank can warm it{via}"
                            ),
                            data=("on-path-wrapper", decl.qualname),
                        )
                    )
        return findings


def _cha_index(graph) -> Dict[str, List]:
    """method name -> decls with that name across the serving-surface
    modules (class-hierarchy lifting for attribute calls)."""
    index: Dict[str, List] = {}
    for path in CHA_MODULES:
        for qualname, decl in graph.by_module.get(path, {}).items():
            method = qualname.rsplit(".", 1)[-1]
            index.setdefault(method, []).append(decl)
    return index


def _reachable(project, graph, cha) -> Dict[Tuple[str, str], str]:
    """BFS over the call graph from the serving roots: decl key ->
    discovery chain (root-first qualname path, for finding messages)."""
    seen: Dict[Tuple[str, str], str] = {}
    queue: List[Tuple] = []
    for root_path, prefix in ROOTS:
        for qualname, decl in graph.by_module.get(root_path, {}).items():
            if qualname == prefix or qualname.startswith(prefix):
                seen[decl.key] = ""
                queue.append(decl)
    while queue:
        decl = queue.pop()
        module = project.module_at(decl.path)
        if module is None:
            continue
        chain = seen[decl.key]
        child_chain = f"{chain} -> {decl.qualname}" if chain else decl.qualname
        current_class = (
            decl.qualname.split(".")[0] if decl.is_method else None
        )
        callees: List = []
        attr_names: Set[str] = set()
        for node in ast.walk(decl.node):
            if not isinstance(node, ast.Call):
                continue
            resolved = graph.resolve(module, node.func, current_class)
            if resolved is not None:
                callees.append(resolved[0])
            elif isinstance(node.func, ast.Attribute):
                attr_names.add(node.func.attr)
        for name in attr_names:
            callees.extend(cha.get(name, ()))
        for callee in callees:
            if callee.key not in seen:
                seen[callee.key] = child_chain
                queue.append(callee)
    return seen
