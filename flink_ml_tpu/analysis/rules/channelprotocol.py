"""channel-protocol: the flow.BoundedChannel lifecycle contract, statically.

docs/flow_control.md states the contract in prose: a worker that can fail
must close its channel with the error (so the consumer re-raises instead
of blocking on a silently-dead producer), every channel must end its life
closed, cancelled, or drained, and the serving push API pairs `submit()`
with a `results()` consumer loop. PR 8 built the runtime to honor it;
nothing *checked* it — the next hand-rolled worker that returns without
closing reintroduces exactly the stall `flow.pump`'s close-with-error
contract was built to kill. Three checks:

- **spawn workers close on all paths** — for every ``flow.spawn(fn,...)``
  call, the worker ``fn`` (resolved through the project call graph:
  module functions and ``self._run`` methods) must (a) reach a channel
  ``close()``/``cancel()`` somewhere — directly or inside a call the
  graph can resolve — and (b) cover its *error* path: the worker body
  must carry a ``try`` whose ``finally`` or exception handler also
  reaches a close, the close-with-error discipline ``serving._run``
  models. (``flow.pump`` needs no check at its call sites: its internal
  worker IS the sanctioned close-with-error implementation.)
- **channels are drained, closed, or cancelled** — a local
  ``flow.BoundedChannel(...)`` construction must, within its function,
  be iterated (``for``/``yield from``), closed/cancelled, handed to
  ``flow.pump`` (which closes it), or passed to a call whose summary
  (`callgraph.Summary.param_closes`) closes that parameter. A channel
  that escapes the function (returned, yielded, stored on ``self``,
  passed to an unresolvable call) gets the benefit of the doubt; one
  that is only ``put``/``get`` and then dropped is a finding.
- **submit() pairs with results()** — a module that calls ``.submit(…)``
  on a server but never touches ``.results`` leaves retired requests
  parked in the results channel until the dispatch worker blocks: the
  push API is a loop, not a fire-and-forget.

Suppression etiquette as everywhere: a deliberate exception carries
``-- <why>`` so the census stays auditable.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import callgraph
from ..engine import Finding, Rule, register
from ..source import SourceModule, dotted_name
from ._astwalk import statements_in_order

_CLOSERS = ("close", "close_with_error", "cancel")


def _flow_call(module: SourceModule, info, call: ast.Call, names: Tuple[str, ...]) -> Optional[str]:
    """'spawn'/'pump'/'BoundedChannel' when ``call`` targets that symbol of
    flink_ml_tpu.flow — via `from .. import flow; flow.spawn(...)` or
    `from ..flow import spawn`."""
    name = dotted_name(call.func)
    if name is None:
        return None
    root, _, rest = name.partition(".")
    if info is not None and root in info.imports:
        target_module, original = info.imports[root]
        # module alias: flow.spawn
        if not rest.count(".") and rest in names:
            dotted = f"{target_module}.{original}"
            if dotted == "flink_ml_tpu.flow" or dotted.endswith(".flow"):
                return rest
        # symbol import: spawn(...)
        if not rest and original in names:
            if target_module == "flink_ml_tpu.flow" or target_module.endswith(".flow"):
                return original
    return None


def _contains_close(node: ast.AST) -> bool:
    """A `.close(...)`/`.cancel(...)` call syntactically inside ``node``
    (nested defs excluded are fine here: a worker defining a closure that
    closes still owns the close)."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _CLOSERS
        ):
            return True
    return False


class _WorkerCheck:
    """Does a spawn worker reach close() on all paths?"""

    def __init__(self, graph: callgraph.CallGraph, project):
        self.graph = graph
        self.project = project

    def _reaches_close(self, decl, depth: int = 0, node: Optional[ast.AST] = None) -> bool:
        """close()/cancel() reachable from ``node`` (default: the whole
        body), following calls the graph resolves, bounded depth."""
        if depth > 4:
            return False
        roots = [node] if node is not None else list(decl.node.body)
        module = self.project.module_at(decl.path)
        current_class = decl.qualname.split(".")[0] if decl.is_method else None
        for root in roots:
            for sub in ast.walk(root):
                if not isinstance(sub, ast.Call):
                    continue
                if (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _CLOSERS
                ):
                    return True
                resolved = self.graph.resolve(module, sub.func, current_class)
                if resolved is not None:
                    callee, _ = resolved
                    if callee.key != decl.key and self._reaches_close(
                        callee, depth + 1
                    ):
                        return True
        return False

    def _error_path_covered(self, decl) -> bool:
        """The worker survives its own death: a top-level try whose
        finally or a broad handler reaches a close."""
        for stmt in decl.node.body:
            if not isinstance(stmt, ast.Try):
                continue
            for block in [stmt.finalbody] + [h.body for h in stmt.handlers]:
                for inner in block or []:
                    if self._reaches_close(decl, node=inner):
                        return True
                    # handler bodies often just call self._fail() etc.
        return False

    def check(self, decl) -> Optional[str]:
        if not self._reaches_close(decl):
            return (
                "spawn worker never closes a channel — a consumer blocked on "
                "its output waits forever once this worker dies or returns; "
                "close()/close(error=...) the channel on every exit path "
                "(or use flow.pump, which owns that contract)"
            )
        if not self._error_path_covered(decl):
            return (
                "spawn worker closes its channel only on the happy path — "
                "wrap the body in try/except so a worker error reaches "
                "close(error=...) (or finally: cancel()); a dead worker "
                "must never silently strand its consumer"
            )
        return None


@register
class ChannelProtocolRule(Rule):
    id = "channel-protocol"
    title = "flow channel lifecycle: close-on-all-paths, drain-or-cancel, submit/results pairing"
    rationale = (
        "flow.BoundedChannel's error contract only works when every "
        "producer closes (with the error) and every consumer drains or "
        "cancels: a worker that returns without closing reintroduces the "
        "silently-dead-producer stall, an undrained channel strands its "
        "blocked producer, and submit() without a results() loop parks "
        "retired requests until the dispatch worker blocks. The rule "
        "checks all three statically, resolving workers and "
        "channel-closing helpers through the project call graph."
    )
    example = (
        "def _run(self):\n"
        "    for item in self._requests:\n"
        "        self._out.put(work(item))\n"
        "    self._out.close()   # finding: no close on the error path\n"
        "flow.spawn(self._run, name='worker')"
    )
    scope = ("flink_ml_tpu",)
    # flow.py implements the contract (pump's close-with-error worker)
    exclude = ("flink_ml_tpu/flow.py",)

    def check_module(self, project, module: SourceModule) -> Iterable[Finding]:
        if module.tree is None:
            return ()
        graph = callgraph.get(project)
        info = graph.jitindex.get(module.path)
        findings: List[Finding] = []
        worker_check = _WorkerCheck(graph, project)

        # -- spawn workers ---------------------------------------------------
        checked_workers: Set[Tuple[str, str]] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _flow_call(module, info, node, ("spawn",))
            if kind != "spawn" or not node.args:
                continue
            worker_expr = node.args[0]
            current_class = self._enclosing_class(module, node)
            resolved = graph.resolve(module, worker_expr, current_class)
            if resolved is None:
                continue  # dynamic worker: benefit of the doubt
            decl, _ = resolved
            if decl.key in checked_workers:
                continue
            checked_workers.add(decl.key)
            message = worker_check.check(decl)
            if message:
                findings.append(
                    Finding(
                        path=module.path,
                        line=node.lineno,
                        rule=self.id,
                        message=f"{decl.qualname}: {message}",
                        data=("worker", decl.qualname),
                    )
                )

        # -- channel constructions drained/closed ----------------------------
        for decl in graph.decls_in(module.path).values():
            findings.extend(self._check_channels(graph, module, info, decl))

        # -- submit/results pairing ------------------------------------------
        submit_line = None
        has_results = False
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "submit"
            ):
                if submit_line is None:
                    submit_line = node.lineno
            if isinstance(node, ast.Attribute) and node.attr == "results":
                has_results = True
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name in (
                "submit",
                "results",
            ):
                has_results = True  # the defining module (serving.py itself)
        if submit_line is not None and not has_results:
            findings.append(
                Finding(
                    path=module.path,
                    line=submit_line,
                    rule=self.id,
                    message=(
                        "submit() without a results() consumer loop — retired "
                        "requests park in the results channel until the "
                        "dispatch worker blocks; iterate results() (or close "
                        "the server) in the same component"
                    ),
                    data=("submit-without-results",),
                )
            )
        return findings

    @staticmethod
    def _enclosing_class(module: SourceModule, target: ast.AST) -> Optional[str]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if sub is target:
                        return node.name
        return None

    def _check_channels(
        self, graph, module, info, decl
    ) -> Iterable[Finding]:
        current_class = decl.qualname.split(".")[0] if decl.is_method else None
        statements = statements_in_order(decl.node.body)
        # channel name -> construction line
        channels: Dict[str, int] = {}
        satisfied: Set[str] = set()
        escaped: Set[str] = set()
        for stmt in statements:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if isinstance(target, ast.Name) and isinstance(stmt.value, ast.Call):
                    if _flow_call(module, info, stmt.value, ("BoundedChannel",)):
                        channels[target.id] = stmt.lineno
                        satisfied.discard(target.id)
                        escaped.discard(target.id)
                        continue
                # ch2 = ch aliasing or self._x = ch escapes
                if isinstance(stmt.value, ast.Name) and stmt.value.id in channels:
                    escaped.add(stmt.value.id)
            if not channels:
                continue
            for node in ast.walk(stmt):
                # close/cancel/iteration
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CLOSERS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in channels
                ):
                    satisfied.add(node.func.value.id)
                elif isinstance(node, ast.Call):
                    kind = _flow_call(module, info, node, ("pump",))
                    chan_args = [
                        a for a in node.args if isinstance(a, ast.Name) and a.id in channels
                    ]
                    if kind == "pump":
                        for a in chan_args:
                            satisfied.add(a.id)
                        continue
                    if not chan_args:
                        continue
                    resolved = graph.resolve(module, node.func, current_class)
                    if resolved is None:
                        for a in chan_args:  # unknown call: benefit of doubt
                            escaped.add(a.id)
                        continue
                    callee, skip_self = resolved
                    closes = graph.summary(callee).param_closes
                    for index, arg in enumerate(node.args):
                        if (
                            isinstance(arg, ast.Name)
                            and arg.id in channels
                        ):
                            if index in closes:
                                satisfied.add(arg.id)
                            else:
                                escaped.add(arg.id)
                elif isinstance(node, ast.YieldFrom) and isinstance(node.value, ast.Name):
                    if node.value.id in channels:
                        satisfied.add(node.value.id)
                elif isinstance(node, (ast.Return, ast.Yield)) and node.value is not None:
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id in channels:
                            escaped.add(sub.id)
                elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Store):
                    pass
            if isinstance(stmt, ast.For) and isinstance(stmt.iter, ast.Name):
                if stmt.iter.id in channels:
                    satisfied.add(stmt.iter.id)
            # self.attr = ch escape
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Attribute) and isinstance(
                        stmt.value, ast.Name
                    ):
                        if stmt.value.id in channels:
                            escaped.add(stmt.value.id)
        for name, line in sorted(channels.items(), key=lambda kv: kv[1]):
            if name in satisfied or name in escaped:
                continue
            yield Finding(
                path=module.path,
                line=line,
                rule=self.id,
                message=(
                    f"channel {name!r} is never drained, closed, or cancelled "
                    "in this function — a producer blocked on its credits "
                    "waits forever; iterate it, close()/cancel() it, or hand "
                    "it to flow.pump"
                ),
                data=("undrained-channel", name),
            )
