"""Incremental-lint summary cache: content-hash-keyed call-graph state.

The interprocedural layer (``analysis/callgraph.py``) walks every
function body in the package to build taint summaries — the dominant
cost of a full lint. But a summary is a pure function of its module's
source *and* the sources of everything it resolves into, so it caches
cleanly:

- Each module's entry is keyed by the sha256 of its source. A hash
  mismatch (or a file the cache has never seen) makes the module
  **dirty**.
- Dirtiness propagates over the *reverse* import graph: a module that
  imports a dirty module may lift different chains through it, so its
  cached summaries cannot be trusted either. The **servable** set is
  therefore ``clean − reverse-closure(dirty)``.
- For servable modules, :meth:`SummaryCache.lookup` hands
  ``CallGraph.analyze`` the deserialized ``(events, summary)`` pair and
  the body walk is skipped entirely; everything else is recomputed and
  re-stored after the run.

Because *events* are cached alongside summaries, a warm run is
finding-identical to a cold run in every mode — full tree or
``--changed`` — which the tier-1 parity test pins
(``tests/test_tpulint.py``). The cache file lives at
``<root>/.tpulint_cache.json`` (gitignored); a corrupt or
version-mismatched file is treated as empty, never trusted.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import DonationSite, Summary, SyncEvent, SyncSite

CACHE_VERSION = 1
DEFAULT_NAME = ".tpulint_cache.json"


def cache_path(root: str) -> str:
    return os.path.join(root, DEFAULT_NAME)


def content_hash(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8")).hexdigest()[:24]


# ---------------------------------------------------------------------------
# (de)serialization — plain JSON, no pickle (the cache is repo-local and
# survives interpreter versions)
# ---------------------------------------------------------------------------

def _site_to_json(site: SyncSite) -> dict:
    return {
        "kind": site.kind,
        "detail": site.detail,
        "path": site.sink_path,
        "line": site.sink_line,
        "funcs": list(site.funcs),
    }


def _site_from_json(d: dict) -> SyncSite:
    return SyncSite(
        kind=d["kind"],
        detail=d["detail"],
        sink_path=d["path"],
        sink_line=int(d["line"]),
        funcs=tuple(d.get("funcs", ())),
    )


def _donation_to_json(site: DonationSite) -> dict:
    return {
        "kernel": site.kernel,
        "path": site.sink_path,
        "line": site.sink_line,
        "funcs": list(site.funcs),
    }


def _donation_from_json(d: dict) -> DonationSite:
    return DonationSite(
        kernel=d["kernel"],
        sink_path=d["path"],
        sink_line=int(d["line"]),
        funcs=tuple(d.get("funcs", ())),
    )


def _summary_to_json(summary: Summary) -> dict:
    return {
        "returnsDevice": summary.returns_device,
        "returnsParams": sorted(summary.returns_params),
        "paramSyncs": [
            [i, [_site_to_json(s) for s in sites]]
            for i, sites in summary.param_syncs
        ],
        "paramDonates": [
            [i, [_donation_to_json(s) for s in sites]]
            for i, sites in summary.param_donates
        ],
        "paramCloses": sorted(summary.param_closes),
    }


def _summary_from_json(d: dict) -> Summary:
    return Summary(
        returns_device=bool(d.get("returnsDevice", False)),
        returns_params=frozenset(int(i) for i in d.get("returnsParams", ())),
        param_syncs=tuple(
            (int(i), tuple(_site_from_json(s) for s in sites))
            for i, sites in d.get("paramSyncs", ())
        ),
        param_donates=tuple(
            (int(i), tuple(_donation_from_json(s) for s in sites))
            for i, sites in d.get("paramDonates", ())
        ),
        param_closes=frozenset(int(i) for i in d.get("paramCloses", ())),
    )


def _sources_to_json(sources) -> list:
    return sorted(sources, key=lambda s: (isinstance(s, str), s))


def _event_to_json(event: SyncEvent) -> dict:
    return {
        "line": event.line,
        "kind": event.kind,
        "detail": event.detail,
        "sources": _sources_to_json(event.sources),
        "path": event.sink_path,
        "sinkLine": event.sink_line,
        "funcs": list(event.funcs),
    }


def _event_from_json(d: dict) -> SyncEvent:
    return SyncEvent(
        line=int(d["line"]),
        kind=d["kind"],
        detail=d["detail"],
        sources=frozenset(
            s if isinstance(s, str) else int(s) for s in d.get("sources", ())
        ),
        sink_path=d["path"],
        sink_line=int(d["sinkLine"]),
        funcs=tuple(d.get("funcs", ())),
    )


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------

class SummaryCache:
    """Loaded cache + the servable set for one run.

    Lifecycle: :func:`load` → :meth:`prepare` (computes dirty/servable
    against a live Project) → lookups during the run →
    :meth:`store_analyses` + :meth:`save` afterwards.
    """

    def __init__(self, path: str, files: Optional[Dict[str, dict]] = None):
        self.path = path
        #: relpath -> {"hash": str, "functions": {qualname: {...}}}
        self.files: Dict[str, dict] = files if files is not None else {}
        self.servable: Set[str] = set()
        self.dirty: Set[str] = set()
        self.hits = 0
        self.misses = 0

    # -- persistence ---------------------------------------------------------
    @classmethod
    def load(cls, path: str) -> "SummaryCache":
        try:
            with open(path) as f:
                payload = json.load(f)
            if payload.get("version") != CACHE_VERSION:
                return cls(path)
            files = payload.get("files", {})
            if not isinstance(files, dict):
                return cls(path)
            return cls(path, files)
        except (OSError, ValueError):
            return cls(path)

    def save(self) -> None:
        tmp = self.path + ".tmp"
        payload = {"version": CACHE_VERSION, "files": self.files}
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- invalidation --------------------------------------------------------
    def prepare(self, project) -> None:
        """Compute this run's dirty and servable sets against the live
        tree: dirty = hash mismatch ∪ never seen; servable = clean −
        reverse-import-closure(dirty). Entries for files no longer on
        disk are dropped."""
        from .rules import _jitindex

        live_hashes: Dict[str, str] = {
            m.path: content_hash(m.source) for m in project.modules
        }
        self.dirty = {
            path
            for path, digest in live_hashes.items()
            if self.files.get(path, {}).get("hash") != digest
        }
        # prune entries whose file vanished (renames/deletions)
        for path in list(self.files):
            if path not in live_hashes:
                del self.files[path]

        # reverse import graph: edge imported -> importer
        index = _jitindex.jit_index(project)
        module_paths = {
            m.module_name: m.path for m in project.modules if m.module_name
        }
        importers: Dict[str, Set[str]] = {}
        for path, info in index.items():
            for target_module, original in info.imports.values():
                for candidate in (
                    module_paths.get(target_module),
                    module_paths.get(f"{target_module}.{original}"),
                ):
                    if candidate is not None and candidate != path:
                        importers.setdefault(candidate, set()).add(path)

        invalid = set(self.dirty)
        frontier = list(self.dirty)
        while frontier:
            current = frontier.pop()
            for importer in importers.get(current, ()):
                if importer not in invalid:
                    invalid.add(importer)
                    frontier.append(importer)
        self.servable = set(live_hashes) - invalid
        self._live_hashes = live_hashes

    # -- run-time API --------------------------------------------------------
    def lookup(
        self, path: str, qualname: str
    ) -> Optional[Tuple[List[SyncEvent], Summary]]:
        if path not in self.servable:
            return None
        entry = self.files.get(path, {}).get("functions", {}).get(qualname)
        if entry is None:
            self.misses += 1
            return None
        try:
            events = [_event_from_json(e) for e in entry.get("events", ())]
            summary = _summary_from_json(entry.get("summary", {}))
        except (KeyError, TypeError, ValueError):
            self.misses += 1
            return None
        self.hits += 1
        return events, summary

    def store_analyses(self, graph) -> None:
        """Fold every analysis the run computed (or re-served) back into
        the cache, under the live content hashes."""
        by_path: Dict[str, Dict[str, dict]] = {}
        for (path, qualname), analysis in graph._analyses.items():
            by_path.setdefault(path, {})[qualname] = {
                "events": [_event_to_json(e) for e in analysis.events],
                "summary": _summary_to_json(analysis.summary),
            }
        for path, digest in getattr(self, "_live_hashes", {}).items():
            entry = self.files.setdefault(path, {"hash": digest, "functions": {}})
            if entry.get("hash") != digest:
                entry["hash"] = digest
                entry["functions"] = {}
            if path in by_path:
                entry["functions"].update(by_path[path])
