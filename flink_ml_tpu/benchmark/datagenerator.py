"""Benchmark data generators — param-driven random table sources.

TPU-native re-design of flink-ml-benchmark/.../datagenerator/ (
DataGenerator.java, InputDataGenerator.java:NUM_VALUES/COL_NAMES/SEED,
common/DenseVectorGenerator.java, DenseVectorArrayGenerator.java,
DoubleGenerator.java, LabeledPointWithWeightGenerator.java,
RandomStringGenerator.java, RandomStringArrayGenerator.java,
clustering/KMeansModelDataGenerator.java). Same param names/JSON configs;
generation is vectorized numpy instead of per-row Flink sources.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..common.param import HasSeed
from ..param import IntParam, LongParam, Param, ParamValidators
from ..table import DictTokenMatrix, Table
from ..utils.lazyjit import lazy_jit

# Rows at or above this threshold are generated directly in device HBM with
# jax.random — the analogue of the reference generating data *inside* the
# cluster (InputTableGenerator.java runs as a Flink source, not a client
# upload). Below it, numpy keeps tiny test tables host-side and cheap.
# NOTE: the two paths draw from different RNGs, so a fixed seed yields
# different values (and float32 vs float64) across the threshold. Set
# FLINK_ML_TPU_DEVICE_DATAGEN=0 to force the numpy path at every size when
# cross-size seeded reproducibility matters more than ingest speed.
# Above this row count, matrix generators birth data directly in device
# HBM. Low on purpose: even an 8MB host-born table costs a tunnel upload
# at fit time (~the whole warm fit wall for the 10k-row demo configs),
# while device generation is a free async dispatch once compiled.
DEVICE_GEN_THRESHOLD = 1_024


_prefer_host = False


def set_prefer_host(value: bool) -> None:
    """Generate the next tables host-side. The runner sets this for stages
    whose compute is inherently host-resident (categorical string
    rendering): device-born data would cross the slow tunnel wholesale.
    Placing birth next to compute is the data-loading layer's job — the
    reference's generator sources likewise run inside the cluster."""
    global _prefer_host
    _prefer_host = value


def _device_gen_enabled() -> bool:
    import os

    if _prefer_host:
        return False
    return os.environ.get("FLINK_ML_TPU_DEVICE_DATAGEN", "1") != "0"


def _uniform_impl(key, shape):
    import jax

    return jax.random.uniform(key, shape, dtype=jax.numpy.float32)


def _randint_float_impl(key, shape, arity):
    import jax

    return jax.random.randint(key, shape, 0, arity).astype(jax.numpy.float32)


# one compiled program per shape (static_argnames); lazy_jit keeps the
# wrappers on the jit.kernels accounting like every other kernel
_uniform_kernel = lazy_jit(_uniform_impl, static_argnames=("shape",))
_randint_kernel = lazy_jit(_randint_float_impl, static_argnames=("shape", "arity"))


def _device_uniform(seed: int, shape):
    import jax

    return _uniform_kernel(jax.random.PRNGKey(seed), tuple(shape))


def _device_randint_float(seed: int, shape, arity: int):
    import jax

    return _randint_kernel(jax.random.PRNGKey(seed), tuple(shape), int(arity))


class _ColNamesParam(Param):
    """String[][] colNames (InputDataGenerator.java COL_NAMES)."""

    def json_encode(self, value):
        return value

    def json_decode(self, json_value):
        return json_value


class DataGenerator(HasSeed):
    """Base generator: getData() -> list of Tables (DataGenerator.java)."""

    NUM_VALUES = LongParam(
        "numValues", "Number of data rows to generate.", 10, ParamValidators.gt(0)
    )
    COL_NAMES = _ColNamesParam("colNames", "Column names of the generated tables.", None)

    def get_num_values(self) -> int:
        return self.get(self.NUM_VALUES)

    def set_num_values(self, value: int):
        return self.set(self.NUM_VALUES, value)

    def get_col_names(self):
        return self.get(self.COL_NAMES)

    def set_col_names(self, *values):
        return self.set(self.COL_NAMES, [list(v) for v in values])

    def _rng(self) -> np.random.RandomState:
        return np.random.RandomState(self.get_seed() % (2**32))

    def get_data(self) -> List[Table]:
        raise NotImplementedError


class DenseVectorGenerator(DataGenerator):
    """Random uniform dense vectors (common/DenseVectorGenerator.java)."""

    VECTOR_DIM = IntParam("vectorDim", "Dimension of generated vectors.", 1, ParamValidators.gt(0))

    def get_vector_dim(self) -> int:
        return self.get(self.VECTOR_DIM)

    def set_vector_dim(self, value: int):
        return self.set(self.VECTOR_DIM, value)

    def get_data(self) -> List[Table]:
        (names,) = self.get_col_names()
        n, d = self.get_num_values(), self.get_vector_dim()
        if n >= DEVICE_GEN_THRESHOLD and _device_gen_enabled():
            X = _device_uniform(self.get_seed() % (2**32), (n, d))
        else:
            X = self._rng().rand(n, d)
        return [Table({names[0]: X})]


class DenseVectorArrayGenerator(DenseVectorGenerator):
    """Arrays of dense vectors per row (common/DenseVectorArrayGenerator.java)."""

    ARRAY_SIZE = IntParam("arraySize", "Size of the vector array.", 1, ParamValidators.gt(0))

    def get_array_size(self) -> int:
        return self.get(self.ARRAY_SIZE)

    def set_array_size(self, value: int):
        return self.set(self.ARRAY_SIZE, value)

    def get_data(self) -> List[Table]:
        from ..linalg import DenseVector

        (names,) = self.get_col_names()
        rng = self._rng()
        n, k, d = self.get_num_values(), self.get_array_size(), self.get_vector_dim()
        col = np.empty(n, dtype=object)
        for i in range(n):
            col[i] = [DenseVector(rng.rand(d)) for _ in range(k)]
        return [Table({names[0]: col})]


class DoubleGenerator(DataGenerator):
    """Random doubles (common/DoubleGenerator.java): uniform [0,1) by
    default; with arity > 0, integer-valued doubles in [0, arity)."""

    ARITY = IntParam(
        "arity",
        "Arity of the generated values: 0 means continuous in [0, 1).",
        0,
        ParamValidators.gt_eq(0),
    )

    def get_arity(self) -> int:
        return self.get(self.ARITY)

    def set_arity(self, value: int):
        return self.set(self.ARITY, value)

    def get_data(self) -> List[Table]:
        # Device-born like the other generators: the scalar consumers
        # (imputer, binarizer, bucketizer) aggregate on device now, and for
        # the remaining host-columnar stages ONE bulk D2H pull (~GB/s) is
        # still cheaper than single-core numpy generation of 1e8+ doubles.
        (names,) = self.get_col_names()
        n, arity = self.get_num_values(), self.get_arity()
        if n >= DEVICE_GEN_THRESHOLD and _device_gen_enabled():
            seed = self.get_seed() % (2**32)
            cols = {}
            for i, name in enumerate(names):
                if arity > 0:
                    cols[name] = _device_randint_float(seed + i, (n,), arity)
                else:
                    cols[name] = _device_uniform(seed + i, (n,))
            return [Table(cols)]
        rng = self._rng()
        if arity > 0:
            return [
                Table({name: rng.randint(0, arity, size=n).astype(np.float64) for name in names})
            ]
        return [Table({name: rng.rand(n) for name in names})]


class LabeledPointWithWeightGenerator(DataGenerator):
    """(features, label, weight) rows (common/LabeledPointWithWeightGenerator.java):
    feature values uniform in [0,1) or categorical of featureArity; label
    uniform integer in [0, labelArity); weight uniform in [0,1)."""

    FEATURE_ARITY = IntParam(
        "featureArity",
        "Arity of each feature: 0 means continuous in [0, 1).",
        2,
        ParamValidators.gt_eq(0),
    )
    LABEL_ARITY = IntParam(
        "labelArity", "Arity of the label.", 2, ParamValidators.gt(1)
    )
    VECTOR_DIM = IntParam("vectorDim", "Dimension of the feature vector.", 1, ParamValidators.gt(0))

    def get_feature_arity(self) -> int:
        return self.get(self.FEATURE_ARITY)

    def set_feature_arity(self, value: int):
        return self.set(self.FEATURE_ARITY, value)

    def get_label_arity(self) -> int:
        return self.get(self.LABEL_ARITY)

    def set_label_arity(self, value: int):
        return self.set(self.LABEL_ARITY, value)

    def get_vector_dim(self) -> int:
        return self.get(self.VECTOR_DIM)

    def set_vector_dim(self, value: int):
        return self.set(self.VECTOR_DIM, value)

    def get_data(self) -> List[Table]:
        (names,) = self.get_col_names()
        n, d = self.get_num_values(), self.get_vector_dim()
        arity = self.get_feature_arity()
        # Categorical tables are device-born like everything else: the
        # categorical consumers (NaiveBayes fit/transform) aggregate on
        # device now, so nothing pulls the table back through the tunnel.
        if n >= DEVICE_GEN_THRESHOLD and _device_gen_enabled():
            seed = self.get_seed() % (2**32)
            if arity == 0:
                X = _device_uniform(seed, (n, d))
            else:
                X = _device_randint_float(seed, (n, d), arity)
            y = _device_randint_float(seed + 1, (n,), self.get_label_arity())
            w = _device_uniform(seed + 2, (n,))
            return [Table({names[0]: X, names[1]: y, names[2]: w})]
        rng = self._rng()
        if arity == 0:
            X = rng.rand(n, d)
        else:
            X = rng.randint(0, arity, size=(n, d)).astype(np.float64)
        y = rng.randint(0, self.get_label_arity(), size=n).astype(np.float64)
        w = rng.rand(n)
        return [Table({names[0]: X, names[1]: y, names[2]: w})]


def _string_vocab(m: int) -> np.ndarray:
    """Decimal token vocabulary at MINIMAL unicode width: astype(str) alone
    yields '<U21' (84 bytes/element), which makes a 10Mx100 token matrix
    17GB and string sorting glacial; '<U{digits}' keeps it 8 bytes at
    m<=100 so the dictionary-encode fast path can view it as int64."""
    return np.arange(m).astype(str).astype(f"<U{len(str(max(m - 1, 1)))}")


class RandomStringGenerator(DataGenerator):
    """Random strings from a fixed-size token universe
    (common/RandomStringGenerator.java)."""

    NUM_DISTINCT_VALUES = IntParam(
        "numDistinctValues", "Number of distinct string values.", 10, ParamValidators.gt(0)
    )

    def get_num_distinct_values(self) -> int:
        return self.get(self.NUM_DISTINCT_VALUES)

    def set_num_distinct_values(self, value: int):
        return self.set(self.NUM_DISTINCT_VALUES, value)

    def get_data(self) -> List[Table]:
        (names,) = self.get_col_names()
        rng = self._rng()
        n, m = self.get_num_values(), self.get_num_distinct_values()
        # vocab fancy-indexing generates fixed-width unicode columns without
        # a per-row Python loop (the reference generates rows inside the
        # cluster; a 10M-iteration host loop here would dominate the stage)
        vocab = _string_vocab(m)
        cols = {}
        for name in names:
            cols[name] = vocab[rng.randint(0, m, size=n)]
        return [Table(cols)]


class RandomStringArrayGenerator(RandomStringGenerator):
    """Arrays of random strings (common/RandomStringArrayGenerator.java)."""

    ARRAY_SIZE = IntParam("arraySize", "Size of the string arrays.", 1, ParamValidators.gt(0))

    def get_array_size(self) -> int:
        return self.get(self.ARRAY_SIZE)

    def set_array_size(self, value: int):
        return self.set(self.ARRAY_SIZE, value)

    def get_data(self) -> List[Table]:
        (names,) = self.get_col_names()
        n, m, k = self.get_num_values(), self.get_num_distinct_values(), self.get_array_size()
        vocab = _string_vocab(m)
        cols = {}
        if n >= DEVICE_GEN_THRESHOLD and _device_gen_enabled():
            # dictionary-encoded, ids born in HBM: string stages compute on
            # the id matrix device-side (a billion-token host loop on the
            # single-core driver would dominate every downstream stage)
            from ..ops import tokens as tokens_ops

            seed = self.get_seed() % (2**32)
            for i, name in enumerate(names):
                ids = tokens_ops.random_token_ids(seed + i, n, k, m)
                cols[name] = DictTokenMatrix(vocab, ids)
            return [Table(cols)]
        rng = self._rng()
        for name in names:
            # (n, k) fixed-width unicode token matrix — the columnar layout
            # string stages consume vectorized (each row is one token array)
            cols[name] = vocab[rng.randint(0, m, size=(n, k))]
        return [Table(cols)]


class KMeansModelDataGenerator(DataGenerator):
    """Random KMeansModelData (clustering/KMeansModelDataGenerator.java)."""

    ARRAY_SIZE = IntParam("arraySize", "Number of centroids.", 2, ParamValidators.gt(0))
    VECTOR_DIM = IntParam("vectorDim", "Dimension of centroids.", 1, ParamValidators.gt(0))

    def get_data(self) -> List[Table]:
        from ..linalg import DenseVector

        (names,) = self.get_col_names()
        rng = self._rng()
        k, d = self.get(self.ARRAY_SIZE), self.get(self.VECTOR_DIM)
        centroids = [DenseVector(rng.rand(d)) for _ in range(k)]
        weights = DenseVector(np.zeros(k))
        return [Table({names[0]: [centroids], names[1]: [weights]})]
