"""Benchmark runner + CLI — JSON-config-driven stage benchmarking.

TPU-native re-design of flink-ml-benchmark (Benchmark.java:45-60,
BenchmarkUtils.java:74-144, BenchmarkResult.java). Config format is the
reference's: a JSON object of named entries, each with a `stage`
{className, paramMap} and an `inputData` generator spec (and optional
`modelData`). Java class names resolve to this framework's classes through
the persistence alias map, so the reference's 36 shipped configs run
unchanged. Results use the reference's schema (totalTimeMs,
inputRecordNum, inputThroughput, outputRecordNum, outputThroughput) plus
one TPU-port extension: phaseTimesMs, the per-phase wall-clock breakdown
(datagen/fit/transform/collect).

CLI: python -m flink_ml_tpu.benchmark <config.json> [--output-file r.json]
     [--profile-dir traces/]   (jax.profiler device trace for TensorBoard)
"""

from __future__ import annotations

import json
import re
import sys
import time

import numpy as np
from typing import Dict, List, Optional

from ..api import AlgoOperator, Estimator, Model
from ..obs import memledger, tracing
from ..table import Table
from ..utils import metrics, read_write

_BENCH_JAVA_PREFIX = "org.apache.flink.ml.benchmark.datagenerator."
_BENCH_PY_MODULE = "flink_ml_tpu.benchmark.datagenerator"


def _resolve_generator(class_name: str):
    import importlib

    if class_name.startswith(_BENCH_JAVA_PREFIX):
        simple = class_name.rsplit(".", 1)[1]
        module = importlib.import_module(_BENCH_PY_MODULE)
        return getattr(module, simple)
    module_name, _, cls_name = class_name.rpartition(".")
    return getattr(importlib.import_module(module_name), cls_name)


def instantiate_generator(spec: Dict):
    cls = _resolve_generator(spec["className"])
    gen = cls()
    for name, json_value in spec.get("paramMap", {}).items():
        param = gen.get_param(name)
        if param is not None:
            gen.set(param, param.json_decode(json_value))
    return gen


def load_config(path: str) -> Dict:
    """Reads a benchmark config; tolerates the reference's // license
    header comments."""
    with open(path) as f:
        text = f.read()
    text = re.sub(r"^\s*//.*$", "", text, flags=re.M)
    return json.loads(text)


def run_benchmark(name: str, entry: Dict) -> Dict:
    """BenchmarkUtils.runBenchmark: generate input, fit/transform the stage,
    time end to end, report throughput — plus a per-phase wall-clock
    breakdown (datagen/fit/transform/collect) the reference's netRuntime
    can't show (the tool that catches host-bound ingestion regressions).

    The result also embeds `metrics` — the registry delta this entry
    produced (per-phase span timers, readback bytes/count, jit compile
    count, collective/datacache counters), so an emitted BENCH json
    carries its own evidence for perf claims."""
    from contextlib import contextmanager

    from ..obs import timeline

    tracing.install_jax_hooks()
    metrics_before = metrics.snapshot()
    timeline_start_us = timeline.now_us()
    hbm_mark = memledger.mark_peak()
    phases: Dict[str, float] = {}

    @contextmanager
    def timed_phase(phase: str):
        start = time.perf_counter()
        with tracing.span("benchmark.phase", benchmark=name, phase=phase):
            try:
                yield
            finally:
                elapsed = time.perf_counter() - start
                phases[phase] = phases.get(phase, 0.0) + elapsed
                metrics.record_time(f"benchmark.{name}.{phase}", elapsed)

    with timed_phase("datagen"):
        stage = read_write.instantiate_with_params(entry["stage"])
        from . import datagenerator as dg

        # stages that declare host-resident compute (Stage.prefers_host_input)
        # get host-born inputs — see set_prefer_host
        dg.set_prefer_host(bool(getattr(stage, "prefers_host_input", False)))
        try:
            input_tables = instantiate_generator(entry["inputData"]).get_data()
        finally:
            dg.set_prefer_host(False)
        _adapt_input_columns(stage, input_tables)
        model_tables: Optional[List[Table]] = None
        if "modelData" in entry:
            model_tables = instantiate_generator(entry["modelData"]).get_data()
            _block_until_ready(model_tables)
        _block_until_ready(input_tables)

    num_input = sum(t.num_rows for t in input_tables)
    start = time.perf_counter()
    # each phase blocks on its own device work so async dispatch can't leak
    # a phase's compute into the next one's timing
    if isinstance(stage, Estimator):
        with timed_phase("fit"):
            model = stage.fit(*input_tables)
        with timed_phase("transform"):
            outputs = model.transform(*input_tables)
            _block_until_ready(outputs)
    elif isinstance(stage, Model) and model_tables is not None:
        with timed_phase("fit"):
            stage.set_model_data(*model_tables)
        with timed_phase("transform"):
            outputs = stage.transform(*input_tables)
            _block_until_ready(outputs)
    elif isinstance(stage, AlgoOperator):
        with timed_phase("transform"):
            outputs = stage.transform(*input_tables)
            _block_until_ready(outputs)
    else:
        raise TypeError(f"Unsupported stage type {type(stage).__name__}")
    with timed_phase("collect"):
        num_output = sum(t.num_rows for t in outputs)
    elapsed_ms = (time.perf_counter() - start) * 1000.0

    delta = metrics.snapshot_delta(metrics_before, metrics.snapshot())
    # dispatch-wall attribution (obs/timeline.py): the work phases' wall
    # split into host-dispatch time (the `iteration.dispatch` funnel —
    # every chunk/fused-program launch rides it) and the GAP the host was
    # not dispatching: device execution + readback + tunnel/idle latency.
    # `dispatchGapMs ~ wallMs - hostDispatchMs` is THE item-2 progress
    # metric: the resident-program work must grow hostDispatch's share of
    # a shrinking wall. gapCount = dispatch->drain cycles (one per chunk).
    work_ms = (phases.get("fit", 0.0) + phases.get("transform", 0.0)) * 1000.0
    disp_timer = delta["timers"].get("iteration.dispatch", {})
    host_dispatch_ms = float(disp_timer.get("totalMs", 0.0))
    gap_count = int(disp_timer.get("count", 0))
    return {
        "name": name,
        "totalTimeMs": elapsed_ms,
        "inputRecordNum": num_input,
        "inputThroughput": num_input * 1000.0 / elapsed_ms if elapsed_ms else 0.0,
        "outputRecordNum": num_output,
        "outputThroughput": num_output * 1000.0 / elapsed_ms if elapsed_ms else 0.0,
        "phaseTimesMs": {k: v * 1000.0 for k, v in phases.items()},
        # first-class dispatch-pipeline fields (also inside metrics):
        # blocking host↔device syncs this entry paid, and the in-flight
        # chunk depth its pipelined loops ran at — a sync-count jump
        # between BENCH files is a dispatch regression
        "hostSyncCount": int(delta["counters"].get("iteration.host_sync", 0)),
        "dispatchDepth": int(delta["gauges"].get("iteration.dispatch_depth", 0)),
        # whole-fit resident-program evidence (parallel/dispatch.py): fits
        # that ran as ONE dispatch + ONE packed readback, and fits that
        # asked to but fell back to the chunked path (per-reason counters
        # inside metrics) — a fallback jump between BENCH files means a
        # config change quietly knocked fits off the resident path
        "wholeFitCount": int(delta["counters"].get("dispatch.whole_fit", 0)),
        "wholeFitFallbacks": int(
            delta["counters"].get("dispatch.whole_fit_fallback", 0)
        ),
        # fleet-training evidence (fleet.py): members this entry trained
        # through the vmapped resident program, and the many-model
        # throughput those fits amortized into the work phases —
        # modelsPerSecond at fleetSize=1 IS the solo fit rate, so a drop
        # at constant fleetSize between BENCH files is a fleet regression
        "fleetSize": (
            int(delta["gauges"].get("fleet.size", 0))
            if delta["counters"].get("fleet.modelsTrained", 0)
            else 0
        ),
        "modelsPerSecond": (
            delta["counters"].get("fleet.modelsTrained", 0)
            / (work_ms / 1000.0)
            if work_ms and delta["counters"].get("fleet.modelsTrained", 0)
            else 0.0
        ),
        "hostDispatchMs": host_dispatch_ms,
        "dispatchGapMs": (
            max(0.0, work_ms - host_dispatch_ms) if gap_count else 0.0
        ),
        "gapCount": gap_count,
        # segments the transform phase fused (0 = eager per-stage path); a
        # drop between BENCH files means stages fell off the fused path
        "fusedSegments": int(delta["gauges"].get("pipeline.fused_segments", 0)),
        # input-pipeline evidence: bytes/transfers this entry pushed
        # host→device through the accounted stager, and the device epoch
        # cache's hit/miss split — an h2dBytes jump between BENCH files is
        # an upload regression (a loop quietly going back to re-uploading
        # its epochs), exactly as hostSyncCount is for readbacks
        "h2dBytes": int(delta["counters"].get("h2d.bytes", 0)),
        "h2dCount": int(delta["counters"].get("h2d.count", 0)),
        "deviceCacheHits": int(delta["counters"].get("devicecache.hit", 0)),
        "deviceCacheMisses": int(delta["counters"].get("devicecache.miss", 0)),
        # checkpoint-subsystem evidence (ckpt/snapshot.py): snapshots this
        # entry wrote and the bytes they gathered — a jump between BENCH
        # files means a loop's snapshot cadence (or payload) changed
        "checkpointCount": int(delta["counters"].get("checkpoint.count", 0)),
        "checkpointBytes": int(delta["counters"].get("checkpoint.bytes", 0)),
        # flow-control evidence (flow.py): transient-fault retries this
        # entry paid, items shed/rejected by overloaded channels, and the
        # deepest any bounded queue got — a retryCount jump between BENCH
        # files means a dependency got flaky, a shed/reject jump means a
        # consumer stopped keeping up, and peakQueueDepth is the memory
        # high-water evidence behind the bounded-overload claim
        "retryCount": int(delta["counters"].get("flow.retry", 0)),
        "shedCount": int(delta["counters"].get("flow.shed", 0)),
        "rejectCount": int(delta["counters"].get("flow.reject", 0)),
        "peakQueueDepth": int(delta["gauges"].get("flow.peakQueueDepth", 0)),
        # device-memory evidence (obs/memledger.py): the peak ledgered
        # HBM bytes this entry touched (watermark over the whole entry,
        # datagen included) and the model constants still resident at
        # entry end — a peakHbmBytes jump between BENCH files means a
        # loop started holding more live at once (the regression the
        # ROADMAP's 2D-mesh and HBM-paging work must not cause), a
        # residentModelBytes jump means published models grew
        "peakHbmBytes": int(memledger.peak_since(hbm_mark)),
        "residentModelBytes": int(memledger.live_bytes("model")),
        # model-lifecycle evidence (lifecycle.py): live model versions this
        # entry published into a serving plan, promotions the gate refused,
        # and health-triggered rollbacks — a promoteRejected jump between
        # BENCH files means the trainer started producing bad candidates,
        # a rollbackCount jump means bad ones started slipping the gate
        "swapCount": int(delta["counters"].get("lifecycle.swap", 0)),
        "rollbackCount": int(delta["counters"].get("lifecycle.rollback", 0)),
        "promoteRejected": int(delta["counters"].get("lifecycle.promoteRejected", 0)),
        # serving-SLO evidence (serving.py + data/modelstore.py): the
        # open-loop load-gen rates a serving entry sustained (0 for
        # non-serving entries — the gauges only exist when a load
        # generator set them), model-store page-ins this entry paid, and
        # the compile count on its serving path — a saturationQps drop or
        # a pageInCount/recompileCount jump between BENCH files is a
        # serving regression (recompileCount is gated zero-tolerance for
        # servingSlo in CI)
        "offeredQps": float(delta["gauges"].get("serving.offeredQps", 0.0)),
        "goodputQps": float(delta["gauges"].get("serving.goodputQps", 0.0)),
        "saturationQps": float(delta["gauges"].get("serving.saturationQps", 0.0)),
        "pageInCount": int(delta["counters"].get("modelstore.pageIn", 0)),
        # per-op collective traffic this entry traced (calls/bytes/chunks
        # from the accounted wrappers in parallel/collectives.py, plus the
        # sparse-vs-dense byte ratio when a sparse reduce ran) — the
        # traffic-proportionality evidence next to the timing numbers
        "collectiveBreakdown": collective_breakdown(delta),
        # per-chunk timeline attribution when the flight recorder is on
        # (wall = dispatch + device + readback + idle-gap, obs/timeline.py)
        "dispatchAttribution": _entry_attribution(timeline, timeline_start_us),
        "metrics": delta,
    }


def _entry_attribution(timeline, start_us: float) -> Optional[Dict]:
    """This entry's dispatch-wall attribution from the flight recorder
    (events recorded since `start_us`); None when the timeline is off or
    no chunk dispatch ran. The per-chunk rows are dropped from the BENCH
    payload (unbounded size) — totals + per-epoch means stay."""
    if not timeline.enabled():
        return None
    events, _ = timeline.snapshot_events()
    attr = timeline.dispatch_attribution(
        [e for e in events if e["tsUs"] >= start_us]
    )
    if not attr:
        return None
    attr.pop("chunks", None)
    return attr


def collective_breakdown(delta: Dict) -> Dict[str, Dict]:
    """Reduce a metrics delta's `collective.<op>.{calls,bytes,chunks}`
    counters into {op: {calls, bytes[, chunks]}} (+ `sparseRatio` from the
    gauge). Empty dict when the entry dispatched no accounted collective."""
    out: Dict[str, Dict] = {}
    for name, value in delta.get("counters", {}).items():
        parts = name.split(".")
        if len(parts) != 3 or parts[0] != "collective":
            continue
        op, field = parts[1], parts[2]
        if field in ("calls", "bytes", "chunks", "dense_equiv_bytes"):
            out.setdefault(op, {})[field] = int(value)
    ratio = delta.get("gauges", {}).get("collective.sparse_ratio")
    if out and ratio is not None:
        out["sparseRatio"] = ratio
    return out


def _adapt_input_columns(stage, input_tables: List[Table]) -> None:
    """Compensate for broken upstream benchmark configs: several reference
    configs (normalizer, maxabsscaler, vectorslicer, elementwiseproduct,
    polynoimalexpansion) generate a single column named 'featuresCol' while
    the stage's input/features param keeps its default ('input'/'features')
    — the stage would fail on the reference too. When the stage's input
    column is missing and the generated table has exactly one column, point
    the stage at that column and log the adaptation."""
    if len(input_tables) != 1 or len(input_tables[0].column_names) != 1:
        return
    only_col = input_tables[0].column_names[0]
    for getter, setter in (
        ("get_input_col", "set_input_col"),
        ("get_features_col", "set_features_col"),
    ):
        if hasattr(stage, getter):
            current = getattr(stage, getter)()
            if current not in input_tables[0] and only_col != current:
                getattr(stage, setter)(only_col)
                print(
                    f"  [config-adapt] {type(stage).__name__}.{getter[4:]}: "
                    f"{current!r} -> {only_col!r} (column absent from generated table)",
                    file=sys.stderr,
                )
            return


def _block_until_ready(tables: List[Table]) -> None:
    """Force device-resident columns to completion so phase timings measure
    real work, not async dispatch. On remote-attached TPUs
    `block_until_ready` can return before the queue drains, so the reliable
    barrier is a scalar READBACK of a probe value that depends on every
    device column (one host round trip total) — including device arrays
    nested inside SparseBatch and DictTokenMatrix columns."""
    import jax
    import jax.numpy as jnp

    from ..table import DictTokenMatrix, SparseBatch

    probes = []
    for t in tables:
        for name in t.column_names:
            col = t.column(name)
            if isinstance(col, SparseBatch):
                arrs = (col.indices, col.values)
            elif isinstance(col, DictTokenMatrix):
                arrs = (col.ids,)
            else:
                arrs = (col,)
            for arr in arrs:
                if isinstance(arr, jax.Array):
                    probes.append(arr[(0,) * arr.ndim].astype(jnp.float32))
    if probes:
        t0 = time.perf_counter()
        # tpulint: disable=host-sync-leak -- this IS the timing barrier: one probe readback, accounted via account_readback below
        host = np.asarray(jnp.stack(probes))
        # the barrier is itself a readback — account it like any other
        tracing.account_readback(host.nbytes, time.perf_counter() - t0, len(probes))


def execute_benchmarks(config: Dict) -> Dict[str, Dict]:
    results = {}
    names = [k for k in config if k != "version"]
    print(f"Found {len(names)} benchmarks.")
    for name in names:
        print(f"Running benchmark {name}.")
        results[name] = run_benchmark(name, config[name])
        r = results[name]
        phase_str = "  ".join(
            f"{k}: {v:.1f}" for k, v in r["phaseTimesMs"].items()
        )
        print(
            f"  totalTimeMs: {r['totalTimeMs']:.1f}  "
            f"inputThroughput: {r['inputThroughput']:.1f} rec/s  [{phase_str}]"
        )
    print("Benchmarks execution completed.")
    return results


def main(argv: List[str]) -> None:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return
    config_path = argv[0]
    output_file = None
    if "--output-file" in argv:
        output_file = argv[argv.index("--output-file") + 1]
    profile_dir = None
    if "--profile-dir" in argv:
        profile_dir = argv[argv.index("--profile-dir") + 1]
    config = load_config(config_path)
    if profile_dir:  # jax.profiler device trace, TensorBoard-loadable
        with metrics.profile_trace(profile_dir):
            results = execute_benchmarks(config)
        print(f"Profiler trace written to {profile_dir}.")
    else:
        results = execute_benchmarks(config)
    if output_file:
        payload = {
            name: {"stage": config[name]["stage"], "results": r}
            for name, r in results.items()
        }
        with open(output_file, "w") as f:
            json.dump(payload, f, indent=2)
        print(f"Benchmark results saved as json in {output_file}.")
    else:
        print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main(sys.argv[1:])
