import sys

from .runner import main

main(sys.argv[1:])
