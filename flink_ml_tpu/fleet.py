"""FitFleet: N estimator fits trained as ONE vmapped resident dispatch.

Whole-fit residency (docs/performance.md §3) collapsed a fit to one
dispatch + one packed readback; this module amortizes N fits into ONE
program — hyperparameter sweeps, CV folds, and per-tenant personalized
models train as a *fleet*. The whole-fit SGD / stream-SGD / Lloyd loops
are vmapped over a leading fleet axis (`ops.optimizer._sgd_fleet_*`,
`models.clustering.kmeans._lloyd_fleet_train`):

- the packed hyper-parameter vector becomes a [N, 5] array, so every
  member carries its own maxIter/tol/lr/reg/elasticNet;
- the per-member convergence mask is the vmapped `while_loop` itself —
  it runs until EVERY member's condition is false and select-freezes
  finished members, so each member's stop epoch and coefficients are
  bit-identical to its solo fit (every contraction in the member bodies
  is vmap-batching bit-stable — see ops/losses.py module docstring);
- the staged dataset is closed over UNBATCHED: input bytes are paid once
  for N models;
- readback is ONE packed [N, result_pack] array.

Sharding over the fleet axis: when N x per-member state crosses
`config.fleet_shard_state_bytes` (and N divides the data shards), the
fleet axis rides the mesh `data` axis — each device owns whole members —
and the training data is replicated instead (`mesh.fleet_sharding`).
Parity per regime: the default (replicated-fleet) regime batches over
the SAME data-sharded reductions as a solo fit, so members are
bit-identical to their solo fits on the same mesh; the fleet-sharded
regime runs each member's reductions over replicated data in
single-shard order, so members are bit-identical to their solo fits on
ONE data shard (and allclose to any shard count — the across-mesh
reduction-order doctrine of docs/fault_tolerance.md).

Fleet checkpointing rides the JobSnapshot coordinator (ckpt/snapshot.py)
as one cut over the fleet-axis-sharded carry (section "fleet", tag
`data`); the memory ledger accounts fleet state under the `fleet`
category, and `hbm.peak.fit` is namespaced per member index
(obs.memledger.record_fleet_fit_peak).

Snap ML's hierarchical data x model scheme (arXiv:1803.06333) and the
batched-objective framing of distributed function minimization ground
the design: many small convex fits are one batched objective to the
hardware.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .parallel import mesh as mesh_lib
from .parallel import prefetch as h2d

__all__ = ["FitFleet", "promote_fleet_winner", "fleet_model_arrays"]

#: estimator class name -> (loss name, validate_binomial). The loss is
#: resolved lazily so importing fleet.py does not pull every model module.
_LINEAR_KINDS = {
    "LogisticRegression": ("binary_logistic", True),
    "LinearSVC": ("hinge", False),
    "LinearRegression": ("least_square", False),
}


def _loss_by_name(name: str):
    from .ops import losses

    return {
        "binary_logistic": losses.BINARY_LOGISTIC_LOSS,
        "hinge": losses.HINGE_LOSS,
        "least_square": losses.LEAST_SQUARE_LOSS,
    }[name]


def _linear_model_for(est):
    """Instantiate the estimator's fitted-model class (mirrors each
    estimator's own `fit` tail: model + update_existing_params)."""
    from .utils.param_utils import update_existing_params

    kind = type(est).__name__
    if kind == "LogisticRegression":
        from .models.classification.logisticregression import LogisticRegressionModel

        model = LogisticRegressionModel()
    elif kind == "LinearSVC":
        from .models.classification.linearsvc import LinearSVCModel

        model = LinearSVCModel()
    else:
        from .models.regression.linearregression import LinearRegressionModel

        model = LinearRegressionModel()
    update_existing_params(model, est)
    return model


def _member_hyper(est) -> List[float]:
    """One member's packed hyper row — the [N, 5] fleet extension of
    `SGD._hyper` ([maxIter, tol, lr, reg, elasticNet], f32)."""
    return [
        float(est.get_max_iter()),
        float(est.get_tol()),
        float(est.get_learning_rate()),
        float(est.get_reg()),
        float(est.get_elastic_net()),
    ]


def _require_same(estimators, getter: str, what: str):
    values = [getattr(e, getter)() for e in estimators]
    if any(v != values[0] for v in values[1:]):
        raise ValueError(
            f"FitFleet members must share {what} (the fleet trains on ONE "
            f"staged dataset / batch schedule); got {sorted(set(map(str, values)))}"
        )
    return values[0]


class FitFleet:
    """Train N same-class estimators as one fleet: `FitFleet([e1..eN])
    .fit(table)` returns N fitted models, each bit-identical to the model
    `ei.fit(table)` would produce solo — in one resident dispatch and one
    packed readback.

    Members must share the structural params that define the staged data
    and batch schedule (featuresCol / labelCol / weightCol /
    globalBatchSize; `k` for KMeans). Per-member hyper-parameters
    (maxIter, tol, learningRate, reg, elasticNet; seed/maxIter for
    KMeans) ride the [N, pack] hyper array and may all differ.

    `shard_fleet_axis` forces (True) or forbids (False) the
    fleet-axis-sharded regime; None decides automatically from
    `config.fleet_shard_state_bytes` and `mesh.fleet_axis_shardable`.
    In the sharded regime data is replicated, so members match their
    solo fits on ONE data shard bit-exactly (module docstring)."""

    def __init__(self, estimators: Sequence, *, shard_fleet_axis: Optional[bool] = None):
        estimators = list(estimators)
        if not estimators:
            raise ValueError("FitFleet needs at least one estimator")
        kind = type(estimators[0]).__name__
        if any(type(e).__name__ != kind for e in estimators):
            raise ValueError(
                "FitFleet members must be the same estimator class; got "
                f"{sorted({type(e).__name__ for e in estimators})}"
            )
        if kind not in _LINEAR_KINDS and kind != "KMeans":
            raise ValueError(
                f"FitFleet does not support {kind}; supported: "
                f"{sorted(_LINEAR_KINDS) + ['KMeans']}"
            )
        self.estimators = estimators
        self.kind = kind
        self.shard_fleet_axis = shard_fleet_axis

    # -- regime ------------------------------------------------------------

    def _decide_sharded(self, mesh, state_bytes: int) -> bool:
        from . import config

        n = len(self.estimators)
        if self.shard_fleet_axis is not None:
            if self.shard_fleet_axis and not mesh_lib.fleet_axis_shardable(mesh, n):
                raise ValueError(
                    f"shard_fleet_axis=True but a fleet of {n} cannot shard "
                    f"over {mesh_lib.num_data_shards(mesh)} data shard(s) "
                    "(needs >1 shards dividing the fleet evenly)"
                )
            return bool(self.shard_fleet_axis)
        return (
            config.fleet_shard_state_bytes is not None
            and state_bytes > config.fleet_shard_state_bytes
            and mesh_lib.fleet_axis_shardable(mesh, n)
        )

    def _stage_fleet_state(self, mesh, n: int, d: int, sharded: bool):
        """Member carry [coeff, grad, wsum, epochs] + criteria, staged
        through the accounted H2D funnel under the `fleet` ledger
        category, fleet-axis-sharded or replicated per the regime."""
        spec2 = (
            mesh_lib.fleet_sharding(mesh, 2) if sharded
            else mesh_lib.replicated_sharding(mesh)
        )
        spec1 = (
            mesh_lib.fleet_sharding(mesh, 1) if sharded
            else mesh_lib.replicated_sharding(mesh)
        )
        carry = (
            h2d.stage_to_device(np.zeros((n, d), np.float32), spec2, category="fleet"),
            h2d.stage_to_device(np.zeros((n, d), np.float32), spec2, category="fleet"),
            h2d.stage_to_device(np.zeros((n,), np.float32), spec1, category="fleet"),
            h2d.stage_to_device(np.zeros((n,), np.int32), spec1, category="fleet"),
        )
        crit = h2d.stage_to_device(
            np.full((n,), np.inf, np.float32), spec1, category="fleet"
        )
        return carry, crit

    @staticmethod
    def _pack_sharding(mesh):
        """The packed [N, result_pack] readback layout: replicated. The
        per-member concatenate inside the vmapped result pack must never
        see a sharded operand on a multi-axis mesh (the GSPMD partial-sum
        miscompile `_pack_train_result` documents), and in the
        fleet-sharded regime an explicit all-gather-on-pack is ONE
        collective at fit end vs. N shard reads at readback."""
        if len(mesh.axis_names) > 1 or mesh_lib.num_data_shards(mesh) > 1:
            return NamedSharding(mesh, P())
        return None

    # -- public API --------------------------------------------------------

    def fit(self, table) -> List:
        """Train every member on `table`; returns the N fitted models (same
        order as the estimators)."""
        from .obs import memledger
        from .utils import metrics

        mesh = mesh_lib.default_mesh()
        n = len(self.estimators)
        metrics.set_gauge("fleet.size", n)
        tok = memledger.mark_peak()
        try:
            if self.kind == "KMeans":
                models = self._fit_kmeans(table, mesh)
            else:
                models = self._fit_linear(table, mesh)
        finally:
            memledger.record_fleet_fit_peak(memledger.peak_since(tok), n)
        metrics.inc_counter("fleet.fits")
        metrics.inc_counter("fleet.modelsTrained", n)
        return models

    # -- linear (SGD) driver -----------------------------------------------

    def _fit_linear(self, table, mesh) -> List:
        from . import config
        from .models import _linear
        from .utils import metrics
        from .ops.losses import sparse_variant
        from .ops.optimizer import SGD
        from .parallel import dispatch, overlap
        from .table import StreamTable

        ests = self.estimators
        loss_name, validate = _LINEAR_KINDS[self.kind]
        loss_func = _loss_by_name(loss_name)
        features_col = _require_same(ests, "get_features_col", "featuresCol")
        label_col = _require_same(ests, "get_label_col", "labelCol")
        weight_col = _require_same(ests, "get_weight_col", "weightCol")
        gbs = int(_require_same(ests, "get_global_batch_size", "globalBatchSize"))
        if validate:
            for est in ests:
                if est.get_multi_class() == "multinomial":
                    raise ValueError(
                        "Multinomial classification is not supported yet. "
                        "Supported options: [auto, binomial]."
                    )
        hyper = np.asarray([_member_hyper(e) for e in ests], np.float32)
        gmax = int(hyper[:, 0].max())
        if self._overlap_requested() and not overlap.fleet_overlap_supported():
            # overlap-scheduled programs cannot host the fleet axis yet;
            # reason-counted so overlap-tuned deployments see the downgrade
            dispatch.account_whole_fit_fallback("fleet_overlap")

        if isinstance(table, StreamTable):
            return self._fit_linear_stream(
                table, mesh, loss_func, hyper, gmax,
                features_col, label_col, weight_col, gbs, validate,
            )

        X, y, w = _linear.extract_train_data(
            table, features_col, label_col, weight_col, keep_sparse=True
        )
        validate_on_device = False
        if validate:
            if isinstance(y, jax.Array):
                validate_on_device = True  # fused into the fleet program
            else:
                _linear.validate_binomial_labels(y)
        if isinstance(X, tuple):  # sparse padded-CSR, never densified
            indices, values, d = X
            X = (indices, values)
            loss_func = sparse_variant(loss_func.name)
        else:
            d = int(X.shape[1])

        # coeff + grad are the dim-proportional member state
        sharded = self._decide_sharded(mesh, state_bytes=2 * len(ests) * d * 4)
        metrics.set_gauge("fleet.sharded", 1.0 if sharded else 0.0)
        template = SGD(global_batch_size=gbs)
        X_b, y_b, w_b = template._batchify(mesh, X, y, w, replicate_data=sharded)
        carry, crit = self._stage_fleet_state(mesh, len(ests), d, sharded)

        flags, coeffs, crits, epochs = self._run_fleet_sgd(
            mesh, X_b, y_b, w_b, carry, crit, loss_func, hyper, gmax, d,
            validate_on_device, sharded, gbs,
        )
        if flags is not None:
            _linear._raise_if_invalid(float(np.min(flags)))
        n_rows = int(y_b.shape[0]) * int(y_b.shape[1])
        metrics.inc_counter(
            "fleet.examplesTrained",
            int(np.sum(epochs)) * (n_rows // max(1, int(y_b.shape[0]))),
        )
        models = []
        for i, est in enumerate(ests):
            model = _linear_model_for(est)
            model.coefficient = np.asarray(coeffs[i], np.float64)
            models.append(model)
        return models

    def _overlap_requested(self) -> bool:
        from . import config

        return bool(config.collective_overlap)

    def _run_fleet_sgd(
        self, mesh, X_b, y_b, w_b, carry, crit, loss_func, hyper, gmax, d,
        check_labels, sharded, gbs,
    ):
        """The fleet SGD loop: ONE whole-fit dispatch + ONE packed readback
        when no checkpoint boundary lands mid-fit, else the chunked path
        with fleet-axis-sharded snapshot cuts. Returns host
        (flags|None, coeffs [N, d], criteria [N], epochs [N])."""
        from . import config
        from .ckpt import faults
        from .ckpt import snapshot as _snapshot
        from .obs import tracing
        from .ops import optimizer as opt
        from .parallel import dispatch
        from .utils.packing import packed_device_get

        n = len(self.estimators)
        pack_sharding = self._pack_sharding(mesh)
        hyper_dev = jnp.asarray(hyper)
        ckpt_dir = config.iteration_checkpoint_dir
        planned = 0

        specs = {"fleet": ("data",) * 5 if sharded else ("replicated",) * 5}
        meta = {
            "numBatches": int(y_b.shape[0]),
            "globalBatchSize": gbs,
            "fleetSize": n,
            "dim": d,
        }
        job_key = self._job_key() if ckpt_dir is not None else None
        interval = max(1, int(config.iteration_checkpoint_interval))
        if ckpt_dir is not None:
            template = tuple(np.zeros(l.shape, l.dtype) for l in carry + (crit,))
            snap = _snapshot.load_job_snapshot(
                ckpt_dir, job_key, templates={"fleet": template}, expect_meta=meta
            )
            if snap is not None:
                leaves = _snapshot.stage_section(
                    snap, "fleet", mesh=mesh, specs=specs["fleet"], category="fleet"
                )
                carry, crit = tuple(leaves[:4]), leaves[4]
                planned = snap.epoch

        take_whole = ckpt_dir is None
        if not take_whole:
            take_whole, _ = dispatch.whole_fit_plan(
                start_epoch=planned, max_iter=gmax, checkpoint_interval=interval
            )

        if take_whole:
            if dispatch.whole_fit_enabled():
                dispatch.account_whole_fit("fleet")
            with tracing.span(
                "iteration.run", mode="fleet", epochs=gmax, fleet=n
            ):
                carry, crit, packed = dispatch.timed_dispatch(
                    opt._sgd_fleet_whole_fit,
                    X_b, y_b, w_b, carry, crit, loss_func, hyper_dev,
                    check_labels, pack_sharding,
                    start=planned, end=gmax,
                )
                (host,) = packed_device_get(packed, sync_kind="fit")
                flags, coeffs, crits, epochs = opt.unpack_fleet_train_result(
                    np.asarray(host), d, check_labels
                )
                if (
                    ckpt_dir is not None
                    and int(epochs.max()) > planned
                    and gmax % interval == 0
                ):
                    _snapshot.save_job_snapshot(
                        ckpt_dir, job_key, {"fleet": carry + (crit,)},
                        epoch=gmax, criteria=float(np.max(crits)),
                        specs=specs, meta=meta,
                    )
                faults.tick("chunk")  # the whole fleet fit is one chunk
            return flags, coeffs, crits, epochs

        # chunked path: the snapshot cadence lands mid-fit
        K = config.iteration_chunk_for(gmax)
        max_iters, tols = hyper[:, 0].astype(np.int64), hyper[:, 1]
        with tracing.span(
            "iteration.run", mode="fleet_chunked", chunk=K, fleet=n
        ):
            stopped = False
            while planned < gmax and not stopped:
                boundary = dispatch.next_boundary(planned, interval)
                end = min(planned + K, gmax, boundary if boundary else gmax)
                with tracing.span("iteration.chunk", epoch=planned, end=end):
                    carry, crit, packed = dispatch.timed_dispatch(
                        opt._sgd_fleet_chunk,
                        X_b, y_b, w_b, carry, crit, loss_func, hyper_dev,
                        jnp.asarray(end, jnp.int32),
                        start=planned, end=end,
                    )
                # ONE packed [N, 2] (epoch, criteria) drain per chunk — the
                # all-members-stopped check needs every member's state
                (chunk_host,) = packed_device_get(packed, sync_kind="drain")
                e_m = np.asarray(chunk_host)[:, 0].astype(np.int64)
                c_m = np.asarray(chunk_host)[:, 1]
                if end % interval == 0:
                    _snapshot.save_job_snapshot(
                        ckpt_dir, job_key, {"fleet": carry + (crit,)},
                        epoch=end, criteria=float(np.max(c_m)),
                        specs=specs, meta=meta,
                    )
                faults.tick("chunk")
                planned = end
                stopped = bool(np.all((e_m >= max_iters) | (c_m <= tols)))
        packed = dispatch.timed_dispatch(
            opt._sgd_fleet_final, carry, crit, hyper_dev, pack_sharding,
            start=planned, end=planned,
        )
        (host,) = packed_device_get(packed, sync_kind="fit")
        flags, coeffs, crits, epochs = opt.unpack_fleet_train_result(
            np.asarray(host), d, False
        )
        if check_labels:
            flag = packed_device_get(
                opt._binomial_labels_ok(y_b), sync_kind="fit"
            )[0]
            flags = np.full((n,), float(flag))
        return flags, coeffs, crits, epochs

    def _job_key(self) -> str:
        """Fleet job identity: "fleet-" + a hash of every member's own
        checkpoint job key, so two fleets differing in ANY member's
        non-termination params write distinct snapshot files."""
        import hashlib

        from .parallel.iteration import checkpoint_job_key

        member_keys = "|".join(checkpoint_job_key(e) for e in self.estimators)
        return f"fleet-{hashlib.sha1(member_keys.encode()).hexdigest()[:10]}"

    # -- linear stream (out-of-core) driver --------------------------------

    def _fit_linear_stream(
        self, table, mesh, loss_func, hyper, gmax,
        features_col, label_col, weight_col, gbs, validate,
    ) -> List:
        """Out-of-core fleet fit: the stream's chunks are stacked into the
        [X | y | w] segment array ONCE (shared across members — the HBM
        segment residency is paid once for N models) and the whole fleet
        trains as one `_sgd_fleet_stream_whole_fit` dispatch."""
        from .models import _linear
        from .obs import tracing
        from .utils import metrics
        from .ops import optimizer as opt
        from .parallel import dispatch
        from .utils.packing import packed_device_get

        ests = self.estimators
        chunks = list(
            _linear._stream_chunks(table, features_col, label_col, weight_col, validate)
        )
        if not chunks:
            raise ValueError("FitFleet stream fit: the stream yielded no batches")
        shapes = {np.shape(X) for X, _, _ in chunks}
        if len(shapes) != 1:
            raise ValueError(
                "FitFleet stream training needs uniform batch shapes "
                f"(got {sorted(shapes)}); ragged tails fall back to solo "
                "fits (dispatch.whole_fit_fallback.ragged_batches)"
            )
        (b, d) = next(iter(shapes))
        nb = len(chunks)
        packed_np = np.stack(
            [
                np.concatenate(
                    [
                        np.asarray(X, np.float32),
                        np.asarray(y, np.float32)[:, None],
                        (
                            np.ones((b, 1), np.float32)
                            if w is None
                            else np.asarray(w, np.float32)[:, None]
                        ),
                    ],
                    axis=1,
                )
                for X, y, w in chunks
            ]
        )
        sharded = self._decide_sharded(mesh, state_bytes=2 * len(ests) * d * 4)
        metrics.set_gauge("fleet.sharded", 1.0 if sharded else 0.0)
        seg_sharding = NamedSharding(
            mesh,
            P() if sharded else P(None, mesh_lib.DATA_AXIS, None),
        )
        packed_all = h2d.stage_to_device(
            packed_np, seg_sharding, category="streamSegments"
        )
        carry, crit = self._stage_fleet_state(mesh, len(ests), d, sharded)
        if dispatch.whole_fit_enabled():
            dispatch.account_whole_fit("fleet")
        with tracing.span(
            "iteration.run", mode="fleet_stream", epochs=gmax, fleet=len(ests)
        ):
            carry, crit, packed = dispatch.timed_dispatch(
                opt._sgd_fleet_stream_whole_fit,
                packed_all, carry, crit, loss_func, jnp.asarray(hyper), d,
                self._pack_sharding(mesh),
                start=0, end=gmax,
            )
            (host,) = packed_device_get(packed, sync_kind="fit")
        _, coeffs, crits, epochs = opt.unpack_fleet_train_result(
            np.asarray(host), d, False
        )
        metrics.inc_counter("fleet.examplesTrained", int(np.sum(epochs)) * b)
        models = []
        for i, est in enumerate(ests):
            model = _linear_model_for(est)
            model.coefficient = np.asarray(coeffs[i], np.float64)
            models.append(model)
        return models

    # -- KMeans (Lloyd) driver ---------------------------------------------

    def _fit_kmeans(self, table, mesh) -> List:
        """N Lloyd fits in one vmapped resident program: the staged point
        set is shared; each member contributes its own seed-derived init
        centroids and maxIter. Readback is ONE [N, k*d + k] pack."""
        from .models.clustering import kmeans as km
        from .obs import tracing
        from .utils import metrics
        from .table import StreamTable, as_dense_matrix
        from .parallel import dispatch
        from .utils.packing import packed_device_get
        from .utils.param_utils import update_existing_params

        if isinstance(table, StreamTable):
            raise ValueError(
                "FitFleet does not support out-of-core KMeans yet; fit "
                "StreamTable KMeans members solo"
            )
        ests = self.estimators
        features_col = _require_same(ests, "get_features_col", "featuresCol")
        k = int(_require_same(ests, "get_k", "k"))
        measure = _require_same(ests, "get_distance_measure", "distanceMeasure")
        X = as_dense_matrix(table.column(features_col), allow_device=True)
        n, d = X.shape
        if n < k:
            raise ValueError(f"Number of points ({n}) is less than k ({k})")
        X_host = np.asarray(X, dtype=np.float32)
        # per-member seeded init: selectRandomCentroids per member
        inits = np.stack(
            [
                X_host[
                    np.random.RandomState(e.get_seed() % (2**32)).choice(
                        n, size=k, replace=False
                    )
                ]
                for e in ests
            ]
        )
        max_iters = np.asarray([int(e.get_max_iter()) for e in ests], np.int32)
        sharded = self._decide_sharded(mesh, state_bytes=2 * len(ests) * k * d * 4)
        metrics.set_gauge("fleet.sharded", 1.0 if sharded else 0.0)
        shards = 1 if sharded else mesh_lib.num_data_shards(mesh)
        n_pad = -(-n // shards) * shards
        mat_sharding = NamedSharding(
            mesh, P() if sharded else P(mesh_lib.DATA_AXIS, None)
        )
        row_sharding = NamedSharding(mesh, P() if sharded else P(mesh_lib.DATA_AXIS))
        X_pad, _ = mesh_lib.pad_to_multiple(X_host, shards)
        X_dev = h2d.stage_to_device(X_pad, mat_sharding)
        w_dev = km._unit_weights(n, n_pad, row_sharding)
        init_spec = (
            mesh_lib.fleet_sharding(mesh, 3) if sharded
            else mesh_lib.replicated_sharding(mesh)
        )
        inits_dev = h2d.stage_to_device(inits, init_spec, category="fleet")
        if dispatch.whole_fit_enabled():
            dispatch.account_whole_fit("fleet")
        gmax = int(max_iters.max())
        with tracing.span(
            "iteration.run", mode="fleet", epochs=gmax, fleet=len(ests)
        ):
            packed = dispatch.timed_dispatch(
                km._lloyd_fleet_train,
                X_dev, w_dev, inits_dev, jnp.asarray(max_iters), measure,
                self._pack_sharding(mesh),
                start=0, end=gmax,
            )
            (host,) = packed_device_get(packed, sync_kind="fit")
        host = np.asarray(host)
        metrics.inc_counter("fleet.examplesTrained", int(np.sum(max_iters)) * n)
        models = []
        for i, est in enumerate(ests):
            model = km.KMeansModel()
            model.centroids = np.asarray(
                host[i, : k * d].reshape(k, d), dtype=np.float64
            )
            model.weights = np.asarray(host[i, k * d :], dtype=np.float64)
            update_existing_params(model, est)
            models.append(model)
        return models


# ---------------------------------------------------------------------------
# fleet -> lifecycle bridge
# ---------------------------------------------------------------------------

def fleet_model_arrays(model) -> Tuple:
    """The swap-protocol array tuple for a fleet-trained model — the same
    leaves the model's `model_arrays()` would publish."""
    if hasattr(model, "centroids"):
        return (
            np.asarray(model.centroids, np.float32),
            np.asarray(model.weights, np.float32),
        )
    return (np.asarray(model.coefficient, np.float32),)


def promote_fleet_winner(lifecycle, models: Sequence, scores: Sequence[float], mode: str = "max"):
    """Promote the fleet winner (by held-out metric) straight into a
    `ModelLifecycle` version ring: picks argmax (`mode="max"`) or argmin
    (`mode="min"`) of `scores`, publishes that member's arrays through
    `lifecycle.promote` (gates, retention, and rollback semantics apply
    unchanged). Returns (winner_index, ModelVersion)."""
    from .utils import metrics

    if len(models) != len(scores):
        raise ValueError(
            f"{len(models)} models but {len(scores)} scores — every fleet "
            "member needs its held-out metric"
        )
    if mode not in ("max", "min"):
        raise ValueError(f"Unknown winner mode {mode!r} (use 'max' or 'min')")
    scores = np.asarray(list(scores), np.float64)
    if np.any(np.isnan(scores)):
        raise ValueError("fleet winner selection got NaN scores")
    winner = int(np.argmax(scores) if mode == "max" else np.argmin(scores))
    version = lifecycle.promote(fleet_model_arrays(models[winner]))
    metrics.inc_counter("fleet.winnerPromoted")
    metrics.set_gauge("fleet.winnerIndex", float(winner))
    metrics.set_gauge("fleet.winnerScore", float(scores[winner]))
    return winner, version
