"""Flow control + transient-fault resilience — the shared runtime contract.

The reference survives production on two mechanisms this port grew without:
credit-based flow control between pipeline stages (Flink's bounded network
buffers — a producer may only emit while it holds a credit from the
consumer) and graceful behavior when a stage is slow or transiently
failing. "Understanding and Optimizing Distributed ML on Spark"
(PAPERS.md) measures the same thing from the outside: stragglers and
overload, not steady-state throughput, dominate real deployments. Before
this module, the Prefetcher, the device-epoch-cache miss stager, the
online-estimator ingest paths and the serving in-flight window each
hand-rolled their own bounded window with no shared policy, any transient
snapshot/spill I/O error was instantly fatal, and an overloaded server
grew its queue until the host fell over. Four pieces, one contract:

1. **`BoundedChannel`** — a credit-based bounded queue between exactly one
   producer role and one consumer role. The producer spends one credit per
   `put`; the consumer returns one per `get`; `credits()` is the live
   allowance. At zero credits the channel's *overload policy* decides:

   | policy        | at zero credits                | guarantees          |
   |---------------|--------------------------------|---------------------|
   | `block`       | producer waits for a credit    | lossless, in-order — |
   |               | (classic backpressure)         | the training default |
   | `shed_oldest` | evict the oldest queued item,  | bounded memory AND  |
   |               | accept the new one             | bounded staleness:  |
   |               |                                | consumed lag < capacity |
   | `sample`      | drop the NEW item (keep the    | bounded memory; the |
   |               | queue — a prefix sample)       | queue stays a faithful |
   |               |                                | prefix, staleness unbounded |
   |   `reject`    | raise `ChannelRejected` — a    | bounded memory AND  |
   |               | typed fast-fail carrying the   | bounded producer    |
   |               | live queue depth               | latency (admission control) |

   Every channel tracks credit accounting in obs counters (`flow.shed`,
   `flow.reject`, the `flow.peakQueueDepth` gauge) and *staleness*: items
   carry an acceptance sequence number, and a `get` records how many
   items were produced after the one being consumed (`max_lag` in
   `stats`, the `flow.lag.<name>` gauge). Under `shed_oldest` the queue
   always holds the newest `capacity` accepted items, so consumed lag is
   strictly below the capacity — the bounded-staleness contract the
   online estimators advertise (docs/flow_control.md).

2. **`pump`** — THE sanctioned worker-thread spawn point (tpulint's
   `unbounded-queue` rule flags raw `threading.Thread` elsewhere): feed an
   iterable through an optional transform into a channel from one daemon
   worker. A worker error closes the channel with the error, which the
   consumer re-raises IN ORDER (after the items staged before the
   failure) — a dead producer can never silently stall a blocked consumer.

3. **`with_retries`** — deadline/backoff wrapper for transiently-failing
   call sites (snapshot write/read, DataCache spill I/O, serving batch
   execution). Exponential backoff with jitter, a bounded retry budget,
   and a strict error taxonomy: only `TRANSIENT_ERRORS` (OSError-family
   plus `TransientError` — the class `ckpt.faults.flaky` injects) are
   retried; everything else — including `ckpt.faults.InjectedFault`,
   which models a *crash*, and data errors like ValueError — propagates
   immediately. An exhausted budget re-raises the ORIGINAL error with
   `retry_attempts` set, so the operator sees the real failure, not a
   wrapper.

4. **`StragglerWatchdog`** — per-stage trailing-mean latency tracking
   (EMA); a sample exceeding `config.straggler_factor` times the trailing
   mean increments `flow.straggler` / `flow.straggler.<stage>` — the obs
   breadcrumb that turns "the job is slow" into "stage X stalled at
   batch N".

Everything here is host-side plumbing: no jax imports, no device state —
safe to use from worker threads and from the lightest unit tests.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

from .obs import timeline
from .utils import metrics

__all__ = [
    "BLOCK",
    "SHED_OLDEST",
    "SAMPLE",
    "REJECT",
    "POLICIES",
    "ChannelClosed",
    "ChannelRejected",
    "ChannelStats",
    "BoundedChannel",
    "pump",
    "spawn",
    "TransientError",
    "TRANSIENT_ERRORS",
    "with_retries",
    "StragglerWatchdog",
    "PersistentStraggler",
]


# ---------------------------------------------------------------------------
# overload policies
# ---------------------------------------------------------------------------

BLOCK = "block"
SHED_OLDEST = "shed_oldest"
SAMPLE = "sample"
REJECT = "reject"
POLICIES = (BLOCK, SHED_OLDEST, SAMPLE, REJECT)


class ChannelClosed(Exception):
    """Raised by `put` on a closed channel, and by `get` once a closed
    channel has drained (iteration turns this into StopIteration)."""


class ChannelRejected(RuntimeError):
    """The `reject` policy's typed fast-fail: the channel was full at
    `put` time. Carries the live queue depth so callers (and their
    clients) can make a load-shedding decision instead of parsing a
    message string."""

    def __init__(self, name: str, depth: int, capacity: int):
        super().__init__(
            f"channel {name!r} rejected put: {depth}/{capacity} credits in use"
        )
        self.channel = name
        self.depth = depth
        self.capacity = capacity


@dataclass
class ChannelStats:
    """Cumulative credit accounting for one channel (all fields are
    monotone except `max_lag`, a high-water mark)."""

    puts: int = 0  # items accepted into the queue
    gets: int = 0  # items handed to the consumer
    shed: int = 0  # items dropped by shed_oldest/sample
    rejected: int = 0  # puts refused by the reject policy
    peak_depth: int = 0  # high-water queue depth
    max_lag: int = 0  # worst consumed staleness (items produced after)


class BoundedChannel:
    """Credit-based bounded queue with a per-consumer overload policy.

    One producer role, one consumer role (each may be a single thread; the
    serving pull loop uses both roles from the same thread via the
    non-blocking `offer`/`get` pair, which never waits). `close(error)`
    ends the stream: the consumer drains the remaining items, then sees
    `error` (re-raised) or clean exhaustion. `cancel()` is the consumer's
    early exit: close AND return whatever was still queued so the caller
    can release resources (staged device buffers, pending guards).
    """

    def __init__(self, capacity: int, policy: str = BLOCK, name: str = "channel"):
        if policy not in POLICIES:
            raise ValueError(f"unknown overload policy {policy!r} (one of {POLICIES})")
        self.capacity = max(1, int(capacity))
        self.policy = policy
        self.name = name
        self.stats = ChannelStats()
        self._cv = threading.Condition()
        self._items: deque = deque()  # (seq, item); bounded by put-side credits
        self._seq = 0  # next acceptance sequence number
        self._closed = False
        self._error: Optional[BaseException] = None

    # -- credit accounting ---------------------------------------------------
    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def credits(self) -> int:
        """Live put allowance: capacity minus queued items."""
        with self._cv:
            return self.capacity - len(self._items)

    def full(self) -> bool:
        with self._cv:
            return len(self._items) >= self.capacity

    # -- producer side -------------------------------------------------------
    def put(self, item, timeout: Optional[float] = None) -> bool:
        """Submit one item under the channel's overload policy. Returns
        True when the item entered the queue, False when the policy
        dropped it (`sample`), raises `ChannelRejected` (`reject`) or
        `ChannelClosed` (consumer gone). `block` waits for a credit, up
        to `timeout` seconds when given (TimeoutError past it)."""
        with self._cv:
            if self.policy == BLOCK:
                deadline = None if timeout is None else time.monotonic() + timeout
                while not self._closed and len(self._items) >= self.capacity:
                    remaining = None
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError(
                                f"channel {self.name!r}: no credit within {timeout}s"
                            )
                    self._cv.wait(remaining)
            if self._closed:
                raise ChannelClosed(self.name)
            if len(self._items) >= self.capacity:
                if self.policy == REJECT:
                    self.stats.rejected += 1
                    metrics.inc_counter("flow.reject")
                    metrics.inc_counter(f"flow.reject.{self.name}")
                    if timeline.enabled():
                        timeline.record_instant(
                            timeline.LANE_FLOW,
                            f"{self.name}.reject",
                            depth=len(self._items),
                        )
                    raise ChannelRejected(self.name, len(self._items), self.capacity)
                self.stats.shed += 1
                metrics.inc_counter("flow.shed")
                metrics.inc_counter(f"flow.shed.{self.name}")
                if timeline.enabled():
                    timeline.record_instant(
                        timeline.LANE_FLOW, f"{self.name}.shed", depth=len(self._items)
                    )
                if self.policy == SAMPLE:  # keep the queue: a prefix sample
                    self._seq += 1  # the dropped item still "happened"
                    return False
                self._items.popleft()  # shed_oldest: evict the stalest
            self._items.append((self._seq, item))
            self._seq += 1
            self.stats.puts += 1
            self._note_depth(len(self._items))
            if timeline.enabled():
                timeline.record_instant(
                    timeline.LANE_FLOW, f"{self.name}.put", depth=len(self._items)
                )
            self._cv.notify_all()
            return True

    def offer(self, item) -> bool:
        """Non-blocking, policy-free put: accept the item iff a credit is
        free right now. The single-threaded pull loops (serving) pair this
        with `get` to keep their window bounded without ever waiting."""
        with self._cv:
            if self._closed:
                raise ChannelClosed(self.name)
            if len(self._items) >= self.capacity:
                return False
            self._items.append((self._seq, item))
            self._seq += 1
            self.stats.puts += 1
            self._note_depth(len(self._items))
            if timeline.enabled():
                timeline.record_instant(
                    timeline.LANE_FLOW, f"{self.name}.put", depth=len(self._items)
                )
            self._cv.notify_all()
            return True

    def _note_depth(self, depth: int) -> None:
        if depth > self.stats.peak_depth:
            self.stats.peak_depth = depth
            if depth > metrics.get_gauge("flow.peakQueueDepth", 0):
                metrics.set_gauge("flow.peakQueueDepth", depth)

    # -- consumer side -------------------------------------------------------
    def get(self, timeout: Optional[float] = None):
        """Take the oldest queued item, waiting up to `timeout` seconds
        (None = indefinitely). Once the channel is closed and drained,
        re-raises the producer's error (in order — queued items always
        deliver first) or `ChannelClosed` on a clean end."""
        with self._cv:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._items and not self._closed:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"channel {self.name!r}: no item within {timeout}s"
                        )
                self._cv.wait(remaining)
            if not self._items:
                if self._error is not None:
                    raise self._error
                raise ChannelClosed(self.name)
            seq, item = self._items.popleft()
            self.stats.gets += 1
            lag = (self._seq - 1) - seq  # items produced after this one
            if lag > self.stats.max_lag:
                self.stats.max_lag = lag
            metrics.set_gauge(f"flow.lag.{self.name}", lag)
            if timeline.enabled():
                timeline.record_instant(
                    timeline.LANE_FLOW,
                    f"{self.name}.get",
                    depth=len(self._items),
                    lag=lag,
                )
            self._cv.notify_all()
            return item

    def __iter__(self) -> Iterator:
        while True:
            try:
                yield self.get()
            except ChannelClosed:
                return

    # -- lifecycle -----------------------------------------------------------
    def close(self, error: Optional[BaseException] = None) -> None:
        """End the stream. Queued items stay consumable; after they drain
        the consumer sees `error` (re-raised) or clean exhaustion. Idempotent
        — the first error wins."""
        with self._cv:
            if error is not None and self._error is None:
                self._error = error
            self._closed = True
            self._cv.notify_all()

    def cancel(self) -> list:
        """Consumer-side early exit: close the channel and return the
        still-queued items so the caller can release what they hold. A
        producer blocked in `put` wakes and sees `ChannelClosed`."""
        with self._cv:
            self._closed = True
            remaining = [item for _, item in self._items]
            self._items.clear()
            self._cv.notify_all()
            return remaining

    @property
    def closed(self) -> bool:
        return self._closed


# ---------------------------------------------------------------------------
# the sanctioned worker spawn: iterable -> channel
# ---------------------------------------------------------------------------

def pump(
    items: Iterable,
    channel: BoundedChannel,
    transform: Optional[Callable[[Any], Any]] = None,
    watchdog: Optional["StragglerWatchdog"] = None,
) -> threading.Thread:
    """Feed `items` (each optionally mapped through `transform`) into
    `channel` from ONE daemon worker thread, then close it. Iteration,
    transform and put all run on the worker, so a single-worker pump keeps
    serial-access constraints (native cache reads, device cache state)
    intact by construction. Error contract: any failure — in the iterable
    or the transform — closes the channel with that error, so the consumer
    re-raises it in order instead of stalling on a silently-dead worker;
    `ChannelClosed` from a consumer's `cancel()` just ends the speculative
    work."""

    def run() -> None:
        try:
            for item in items:
                if transform is not None:
                    if watchdog is not None:
                        with watchdog.observe():
                            item = transform(item)
                    else:
                        item = transform(item)
                channel.put(item)
        except ChannelClosed:
            pass  # consumer cancelled: abandon speculative staging
        except BaseException as e:  # noqa: BLE001 — the channel IS the error path
            channel.close(error=e)
            return
        channel.close()

    worker = threading.Thread(target=run, name=f"flow-pump-{channel.name}", daemon=True)
    worker.start()
    return worker


def spawn(fn: Callable[[], None], name: str = "worker") -> threading.Thread:
    """Start a named daemon worker running `fn` — the escape hatch for
    loops that don't fit `pump`'s iterable→channel shape (the serving
    dispatch loop). Callers own their error handling: a worker that can
    fail must route the failure into a channel via `close(error)`, never
    swallow it. Lives here so tpulint's `unbounded-queue` rule can pin
    every thread spawn in the tree to this module."""
    worker = threading.Thread(target=fn, name=f"flow-{name}", daemon=True)
    worker.start()
    return worker


# ---------------------------------------------------------------------------
# retry-with-backoff for transient faults
# ---------------------------------------------------------------------------

class TransientError(RuntimeError):
    """Base class for failures that are retryable BY CONTRACT: the caller
    may re-execute the failed operation verbatim and expect success
    (flaky I/O, a preempted RPC). `ckpt.faults.TransientFault` — the
    injectable flavor — subclasses this; `ckpt.faults.InjectedFault`
    deliberately does NOT (it models a crash, and retrying a crash would
    un-test the checkpoint path)."""


#: The retryable taxonomy: OS-level I/O flakes plus contract-transient
#: errors. ValueError/TypeError/KeyError-class data errors, InjectedFault
#: kills, and everything else propagate on the first failure.
TRANSIENT_ERRORS: Tuple[type, ...] = (OSError, TimeoutError, ConnectionError, TransientError)


def with_retries(
    fn: Callable,
    *args,
    site: str = "",
    retries: Optional[int] = None,
    base_delay_s: Optional[float] = None,
    max_delay_s: Optional[float] = None,
    deadline_s: Optional[float] = None,
    retryable: Optional[Tuple[type, ...]] = None,
    on_retry: Optional[Callable[[BaseException, int], None]] = None,
    **kwargs,
):
    """Call `fn(*args, **kwargs)`, retrying transient failures with
    exponential backoff + jitter.

    - `retries` is the retry BUDGET (extra attempts after the first);
      default `config.transient_retries`, 0 = fail on first error.
    - Only `retryable` errors (default `TRANSIENT_ERRORS`) are retried;
      anything else propagates immediately.
    - `deadline_s` bounds total wall time including backoff sleeps: once
      exceeded, no further attempt is made.
    - An exhausted budget re-raises the ORIGINAL error with
      `retry_attempts` set to the number of calls made — the failure the
      operator debugs is the real one, with the retry evidence attached.
    - Every retry increments `flow.retry` (and `flow.retry.<site>`), the
      counters the benchmark runner lifts into first-class BENCH fields.
    """
    from . import config

    budget = config.transient_retries if retries is None else int(retries)
    base = config.retry_base_delay_s if base_delay_s is None else float(base_delay_s)
    cap = config.retry_max_delay_s if max_delay_s is None else float(max_delay_s)
    classes = TRANSIENT_ERRORS if retryable is None else retryable
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except classes as e:  # type: ignore[misc]
            out_of_budget = attempt > budget
            out_of_time = (
                deadline_s is not None and time.monotonic() - start >= deadline_s
            )
            if out_of_budget or out_of_time:
                e.retry_attempts = attempt  # evidence on the ORIGINAL error
                raise
            metrics.inc_counter("flow.retry")
            if site:
                metrics.inc_counter(f"flow.retry.{site}")
            if timeline.enabled():
                timeline.record_instant(
                    timeline.LANE_FLOW,
                    f"retry.{site or 'unsited'}",
                    attempt=attempt,
                    error=type(e).__name__,
                )
            if on_retry is not None:
                on_retry(e, attempt)
            delay = min(cap, base * (2 ** (attempt - 1)))
            # full jitter (50-100% of the backoff step): retries from
            # concurrent sites decorrelate instead of stampeding together
            time.sleep(delay * (0.5 + 0.5 * random.random()))


# ---------------------------------------------------------------------------
# straggler watchdog
# ---------------------------------------------------------------------------

class PersistentStraggler(RuntimeError):
    """The watchdog's typed escalation (opt-in via
    `config.straggler_escalate` or the `escalate` ctor arg): one stage
    was flagged on `consecutive` samples IN A ROW — no longer a blip the
    EMA will absorb but a stage that has durably stopped keeping up, the
    input a supervisor can act on (quarantine, re-dispatch, abort)
    where a counter is only a breadcrumb. Carries the evidence so the
    handler never parses a message string."""

    def __init__(self, stage: str, consecutive: int, seconds: float, mean_s: float):
        super().__init__(
            f"stage {stage!r} straggled on {consecutive} consecutive samples "
            f"(last {seconds * 1000.0:.1f}ms vs trailing mean "
            f"{mean_s * 1000.0:.1f}ms)"
        )
        self.stage = stage
        self.consecutive = consecutive
        self.seconds = seconds
        self.mean_s = mean_s


class StragglerWatchdog:
    """Flag stage executions that exceed a multiple of the stage's
    trailing-mean latency.

    The trailing mean is an EMA (`alpha`); the first `warmup` samples
    only seed it (cold caches and first-call compiles are not
    stragglers). A flagged sample increments `flow.straggler` and
    `flow.straggler.<stage>` and publishes the offending latency as the
    `flow.straggler.<stage>.lastMs` gauge — obs counters, not exceptions:
    a straggler is a symptom to surface, not a failure to inject.

    Escalation (opt-in): with `escalate` set (ctor arg, falling back to
    `config.straggler_escalate`; 0 = off), `record` raises a typed
    `PersistentStraggler` once that many consecutive samples flag — the
    counter stays a symptom, the streak becomes a failure. A healthy
    sample resets the streak, and the escalating sample still folds into
    the mean first, so a caller that catches and continues observes the
    same trailing mean as a non-escalating watchdog."""

    def __init__(
        self,
        stage: str,
        factor: Optional[float] = None,
        warmup: int = 5,
        alpha: float = 0.25,
        escalate: Optional[int] = None,
    ):
        self.stage = stage
        self._factor = factor
        self.warmup = max(1, int(warmup))
        self.alpha = float(alpha)
        self._escalate = escalate
        self._mean = 0.0
        self._n = 0
        self._streak = 0  # consecutive flagged samples

    @property
    def factor(self) -> float:
        if self._factor is not None:
            return self._factor
        from . import config

        return config.straggler_factor

    @property
    def escalate_after(self) -> int:
        """Consecutive flags that raise `PersistentStraggler` (0 = never)."""
        if self._escalate is not None:
            return max(0, int(self._escalate))
        from . import config

        return max(0, int(config.straggler_escalate))

    @property
    def trailing_mean_s(self) -> float:
        return self._mean

    @property
    def samples(self) -> int:
        """Samples folded so far (warmup arming rides on this)."""
        return self._n

    @property
    def consecutive_flags(self) -> int:
        return self._streak

    @contextmanager
    def observe(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(time.perf_counter() - t0)

    def record(self, seconds: float) -> bool:
        """Fold one latency sample; returns True when it was flagged.
        Raises `PersistentStraggler` when escalation is armed and this
        sample extends the consecutive-flag streak to the threshold."""
        flagged = (
            self._n >= self.warmup
            and self._mean > 0.0
            and seconds > self.factor * self._mean
        )
        if flagged:
            metrics.inc_counter("flow.straggler")
            metrics.inc_counter(f"flow.straggler.{self.stage}")
            metrics.set_gauge(f"flow.straggler.{self.stage}.lastMs", seconds * 1000.0)
        # stragglers still fold into the mean: a stage that got
        # permanently slower stops being flagged once the mean catches up
        mean_before = self._mean
        self._mean = (
            seconds
            if self._n == 0
            else (1.0 - self.alpha) * self._mean + self.alpha * seconds
        )
        self._n += 1
        self._streak = self._streak + 1 if flagged else 0
        threshold = self.escalate_after
        if flagged and threshold and self._streak >= threshold:
            metrics.inc_counter("flow.straggler.escalated")
            metrics.inc_counter(f"flow.straggler.{self.stage}.escalated")
            self._streak = 0  # a caller that catches and continues re-arms
            raise PersistentStraggler(self.stage, threshold, seconds, mean_before)
        return flagged
