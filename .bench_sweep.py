"""Timing sweep: run every conf/ config once at reference size, log per-stage times."""
import glob
import json
import os
import sys
import time
import traceback

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(__file__), ".jax_cache"))
import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

from flink_ml_tpu.benchmark import runner

results = {}
paths = sorted(glob.glob("conf/*.json"))
for path in paths:
    config = runner.load_config(path)
    for name, entry in config.items():
        if name == "version":
            continue
        t0 = time.perf_counter()
        try:
            r = runner.run_benchmark(name, entry)
            wall = time.perf_counter() - t0
            results[path] = {"name": name, "wallS": wall, "result": r}
            print(f"{os.path.basename(path):45s} {wall:8.1f}s  total {r['totalTimeMs']:9.1f}ms  thr {r['inputThroughput']:12.1f} rec/s", flush=True)
        except Exception as e:
            wall = time.perf_counter() - t0
            results[path] = {"name": name, "wallS": wall, "error": repr(e)}
            print(f"{os.path.basename(path):45s} {wall:8.1f}s  ERROR {e!r}", flush=True)
            traceback.print_exc()

with open(".bench_sweep_results.json", "w") as f:
    json.dump(results, f, indent=2)
print("done", flush=True)
