"""Benchmark driver — prints ONE JSON line with the headline metric.

Mirrors the reference's only published number: the flink-ml-benchmark README
KMeans example (10,000 DenseVectors × dim 10, k=2 default params, seed 2)
which reports totalTimeMs=7148 / inputThroughput=1398.99 records/s on a
local Flink cluster (flink-ml-benchmark/README.md:100-110, BASELINE.md).
Timing matches the reference's method — wall clock around the whole
fit+collect job (BenchmarkUtils.java:131-144), which for us includes JIT
compilation, host→device transfer and the full training loop.

The north-star LogisticRegression workload
(logisticregression-benchmark.json: 10M × dim 100, maxIter 20,
globalBatchSize 100k) is also run and reported on stderr; it has no
published reference number yet (BASELINE.json "published": {}).

Usage: python bench.py [--skip-logreg] [--logreg-rows N]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_KMEANS_THROUGHPUT = 1398.9927252378288  # records/s, README.md:104-108


def _enable_compilation_cache():
    """Persist compiled XLA programs across runs — steady-state numbers then
    survive process restarts (the deployment configuration)."""
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass


def _timed_fit(make_stage, table, repeats: int = 2):
    """fit + collect model data, `repeats` times on identical shapes; returns
    (cold_seconds, warm_seconds). The warm run is steady state: compilation
    cached, data transfer and the full training loop still included."""
    times = []
    for _ in range(max(1, repeats)):
        start = time.perf_counter()
        model = make_stage().fit(table)
        for t in model.get_model_data():
            t.collect()
        times.append(time.perf_counter() - start)
    return times[0], min(times[1:] or times)


def bench_kmeans():
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.table import Table

    rng = np.random.RandomState(2)
    X = rng.rand(10_000, 10)
    table = Table({"features": X})

    cold, warm = _timed_fit(lambda: KMeans().set_k(2).set_seed(2), table)
    return {
        "coldTimeMs": cold * 1000.0,
        "totalTimeMs": warm * 1000.0,
        "inputRecordNum": X.shape[0],
        "inputThroughput": X.shape[0] / warm,
    }


def bench_logreg(num_rows: int):
    from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
    from flink_ml_tpu.table import Table

    dim = 100
    rng = np.random.default_rng(2)
    X = rng.random((num_rows, dim), dtype=np.float32)
    truth = rng.random(dim, dtype=np.float32) - 0.5
    y = (X @ truth > 0).astype(np.float32)
    table = Table({"features": X, "label": y})

    def make():
        return (
            LogisticRegression()
            .set_max_iter(20)
            .set_learning_rate(0.1)
            .set_global_batch_size(min(100_000, num_rows))
            .set_tol(1e-6)
        )

    cold, warm = _timed_fit(make, table)
    return {
        "coldTimeMs": cold * 1000.0,
        "totalTimeMs": warm * 1000.0,
        "inputRecordNum": num_rows,
        "inputThroughput": num_rows / warm,
    }


def main(argv):
    _enable_compilation_cache()
    skip_logreg = "--skip-logreg" in argv
    logreg_rows = 10_000_000
    if "--logreg-rows" in argv:
        try:
            logreg_rows = int(argv[argv.index("--logreg-rows") + 1])
        except (IndexError, ValueError):
            print("--logreg-rows needs an integer; using default", file=sys.stderr)

    kmeans = bench_kmeans()
    print(
        f"kmeans: warm {kmeans['totalTimeMs']:.0f} ms / cold {kmeans['coldTimeMs']:.0f} ms, "
        f"{kmeans['inputThroughput']:.0f} records/s "
        f"(reference baseline: 7148 ms, {BASELINE_KMEANS_THROUGHPUT:.0f} records/s)",
        file=sys.stderr,
    )
    if not skip_logreg:
        try:
            logreg = bench_logreg(logreg_rows)
            print(
                f"logisticregression ({logreg_rows} x 100): "
                f"warm {logreg['totalTimeMs']:.0f} ms / cold {logreg['coldTimeMs']:.0f} ms, "
                f"{logreg['inputThroughput']:.0f} records/s (no published baseline)",
                file=sys.stderr,
            )
        except Exception as e:  # the headline metric must still print
            print(f"logisticregression benchmark failed: {e!r}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "kmeans_train_input_throughput",
                "value": round(kmeans["inputThroughput"], 2),
                "unit": "records/s",
                "vs_baseline": round(
                    kmeans["inputThroughput"] / BASELINE_KMEANS_THROUGHPUT, 2
                ),
            }
        )
    )


if __name__ == "__main__":
    main(sys.argv[1:])
