"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: the north-star workload from BASELINE.md — the reference's
logisticregression-benchmark.json (10M points x dim 100, maxIter 20,
globalBatchSize 100k, flink-ml-benchmark/src/main/resources/
logisticregression-benchmark.json) — reported as training records/s/chip.

The reference publishes no CPU number for this workload, so `vs_baseline`
is measured here against a same-process numpy implementation of the exact
reference SGD semantics (SGD.java:82-292 math, same batch schedule, same
timing method: wall clock around datagen+fit, BenchmarkUtils.java:131-144).
That numpy run is a *stronger* baseline than the reference's Flink job
(pure BLAS, no streaming-engine overhead), so the reported ratio is a
lower bound on the speedup over the actual reference.

Also reported inside the same JSON line (details):
- loss parity: TPU final loss vs the numpy reference-semantics loss on an
  identical workload (must match to float32 tolerance);
- an MFU estimate for the training loop (flops model: 4*B*d per epoch —
  the X@coeff and X.T@mult MXU contractions);
- the KMeans README workload (10k x dim 10, k=2) vs its published
  1398.99 records/s (flink-ml-benchmark/README.md:100-110).

Budget-proof: every stage runs under an internal wall-clock budget
(BENCH_BUDGET_S, default 420s) and the headline JSON ALWAYS prints —
stages that miss the budget or crash appear as nulls in details.

Usage: python bench.py [--logreg-rows N] [--skip-parity] [--skip-cpu]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_KMEANS_THROUGHPUT = 1398.9927252378288  # records/s, README.md:104-108
DIM = 100
MAX_ITER = 20
BATCH = 100_000
LR_RATE = 0.1
TOL = 1e-6


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _enable_compilation_cache():
    """Persist compiled XLA programs across runs — steady-state numbers then
    survive process restarts (the deployment configuration). Routed through
    the library knob (docs/performance.md §4) so bench runs exercise the
    same code path users get from config.enable_compilation_cache()."""
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        from flink_ml_tpu import config

        config.enable_compilation_cache(cache_dir)
    except Exception:
        pass


def _make_logreg(num_rows, max_iter=MAX_ITER):
    from flink_ml_tpu.models.classification.logisticregression import LogisticRegression

    return (
        LogisticRegression()
        .set_max_iter(max_iter)
        .set_learning_rate(LR_RATE)
        .set_global_batch_size(min(BATCH, num_rows))
        .set_tol(TOL)
        .set_weight_col("weight")
    )


def _gen_table(num_rows, seed):
    """The reference benchmark's input source (LabeledPointWithWeightGenerator,
    logisticregression-benchmark.json inputData) — data born in the cluster
    there, born in device HBM here."""
    from flink_ml_tpu.benchmark.datagenerator import LabeledPointWithWeightGenerator

    gen = (
        LabeledPointWithWeightGenerator()
        .set_col_names(["features", "label", "weight"])
        .set_num_values(num_rows)
        .set_vector_dim(DIM)
        .set_feature_arity(0)
        .set_seed(seed)
    )
    return gen.get_data()[0]


def bench_logreg(num_rows, in_budget=lambda: True):
    """North-star workload. Reports cold (includes XLA compile) and warm
    end-to-end job times (datagen + fit, the reference's netRuntime span).
    Because gen and fit are NOT separated by a device sync (see loop note),
    fitTimeMs absorbs the pipelined device-side datagen: it is the span
    from fit() call to model-on-host, and trainLoopMFU computed from it is
    a lower bound on the true train-loop MFU. totalTimeMs (gen dispatch +
    fit) is the honest job span and the basis of every throughput number."""
    import jax

    runs = []
    fit_times = []
    # 5 runs: run 0 is cold (compile); the min over the warm runs smooths
    # the remote tunnel's ~100ms round-trip jitter, which otherwise moves
    # the headline by tens of percent between invocations
    for i in range(5):
        if i > 0 and len(runs) > 1 and not in_budget():
            break
        # No sync between gen and fit: generation, batching, and training
        # pipeline as async dispatches, and fit's single packed readback is
        # the only host round trip. t_gen+t_fit still spans datagen through
        # model-on-host (the reference's netRuntime span) — fit just absorbs
        # the device-side generation time.
        t0 = time.perf_counter()
        table = _gen_table(num_rows, seed=2 + i)
        t_gen = time.perf_counter() - t0
        t0 = time.perf_counter()
        model = _make_logreg(num_rows).fit(table)
        t_fit = time.perf_counter() - t0
        runs.append(t_gen + t_fit)
        fit_times.append(t_fit)
        log(
            f"logreg run {i}: gen {t_gen * 1000:.0f} ms + fit {t_fit * 1000:.0f} ms"
            + (" (cold: includes compile)" if i == 0 else "")
        )
    warm = min(runs[1:])
    warm_fit = min(fit_times[1:])
    # FLOPs model: per epoch, X@coeff and X.T@multiplier over one batch =
    # 2*(2*B*d); peak for this chip read from jax, fallback 197 TF/s bf16-ish.
    flops = MAX_ITER * 4.0 * min(BATCH, num_rows) * DIM
    # Peak flops for MFU: override with BENCH_PEAK_FLOPS for other parts;
    # default ~197e12 (v5e-class bf16 peak).
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))
    mfu = flops / warm_fit / peak
    n_chips = jax.device_count()
    return {
        "coldTimeMs": runs[0] * 1000.0,
        "totalTimeMs": warm * 1000.0,
        "fitTimeMs": warm_fit * 1000.0,
        "inputRecordNum": num_rows,
        "inputThroughput": num_rows / warm,
        "throughputPerChip": num_rows / warm / n_chips,
        "numChips": n_chips,
        # flop-model fallback; overwritten with the profiler-trace MFU by
        # the trace stage when it runs (trainLoopMFUSource says which)
        "trainLoopMFU": mfu,
        "trainLoopMFUSource": "flop_model_fallback",
    }


def bench_logreg_trace(num_rows):
    """Profiler-trace evidence for the headline fit (round-3/4 ask): ONE
    warm fit under jax.profiler, reduced to device-busy time, measured HBM
    traffic, and executed FLOPs — the MFU from the device timeline rather
    than a flop model, and an explicit name for what the wall actually is
    (device compute vs the remote tunnel's dispatch+readback latency)."""
    from flink_ml_tpu.utils.traceprof import capture_trace

    table = _gen_table(num_rows, seed=2)
    np.asarray(table.column("label")[:1])  # barrier: keep datagen off the trace
    stats = capture_trace(lambda: _make_logreg(num_rows).fit(table))
    if "error" in stats:
        return stats
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))
    peak_hbm = float(os.environ.get("BENCH_PEAK_HBM_GBPS", "819"))  # v5e-class
    busy_s = stats["deviceBusyMs"] / 1000.0
    stats["peakFlops"] = peak
    stats["trainLoopMFU_trace"] = (
        stats["modelFlops"] / busy_s / peak if busy_s > 0 else None
    )
    # this workload is bandwidth-bound (arithmetic intensity ~0.5 flop/byte),
    # so HBM utilization, not MFU, is the roofline that matters
    stats["peakHbmGBps"] = peak_hbm
    stats["hbmUtilization"] = (
        stats["hbmGBps"] / peak_hbm if stats["hbmGBps"] is not None else None
    )
    stats["hostDispatchMs"] = stats["wallMs"] - stats["deviceBusyMs"]
    stats["wallIs"] = (
        "tunnel-dispatch+readback-latency"
        if stats["deviceBusyMs"] < 0.5 * stats["wallMs"]
        else "device-compute"
    )
    if stats["hbmGBps"] is not None:
        log(
            f"trace: wall {stats['wallMs']:.0f} ms, device busy {stats['deviceBusyMs']:.1f} ms, "
            f"HBM {stats['hbmGBps']:.0f} GB/s ({stats['hbmUtilization']:.0%} of roofline), "
            f"MFU(trace) {stats['trainLoopMFU_trace']:.4f}, wall is {stats['wallIs']}"
        )
    else:
        log(f"trace: wall {stats['wallMs']:.0f} ms, no device activity recorded")
    return stats


def bench_logreg_amortized(num_rows, max_iter=200, in_budget=lambda: True):
    """Same headline workload at maxIter 200: amortizes the fixed ~100ms
    tunnel dispatch+readback floor over 10x the training work, showing the
    train loop's own throughput. trainedExamplesPerSec counts SGD work
    actually done (batch records x epochs per second); epochMsAmortized is
    the per-epoch cost once the fixed floor is spread thin."""
    from flink_ml_tpu.obs import timeline
    from flink_ml_tpu.utils import metrics

    runs = []
    last_attr = None
    last_dispatch_ms = 0.0
    for i in range(3):
        if i > 0 and len(runs) > 1 and not in_budget():
            break
        # flight-record the warm runs: the per-fit dispatch-wall
        # attribution (wall = dispatch + device + readback + idle-gap)
        # is the item-2 evidence next to the throughput number
        if i > 0:
            timeline.configure(ring_size=16384)
        mark_us = timeline.now_us()
        before = metrics.snapshot()
        t0 = time.perf_counter()
        table = _gen_table(num_rows, seed=2 + i)
        _make_logreg(num_rows, max_iter=max_iter).fit(table)
        runs.append(time.perf_counter() - t0)
        if i > 0:
            events, _ = timeline.snapshot_events()
            attr = timeline.dispatch_attribution(
                [e for e in events if e["tsUs"] >= mark_us]
            )
            if attr:
                attr.pop("chunks", None)
                last_attr = attr
            delta = metrics.snapshot_delta(before, metrics.snapshot())
            last_dispatch_ms = delta["timers"].get("iteration.dispatch", {}).get(
                "totalMs", 0.0
            )
            timeline.configure()
        log(
            f"logreg maxIter={max_iter} run {i}: {runs[-1] * 1000:.0f} ms"
            + (" (cold: includes compile)" if i == 0 else "")
        )
    warm = min(runs[1:] or runs)
    return {
        "maxIter": max_iter,
        "coldTimeMs": runs[0] * 1000.0,
        "totalTimeMs": warm * 1000.0,
        "inputRecordNum": num_rows,
        "inputThroughput": num_rows / warm,
        "trainedExamplesPerSec": min(BATCH, num_rows) * max_iter / warm,
        "epochMsAmortized": warm * 1000.0 / max_iter,
        # host-side dispatch time of the LAST warm fit and its residual
        # gap (device + readback + idle): the measurable form of the
        # "wall is tunnel-dispatch+readback" verdict, per run
        "hostDispatchMs": last_dispatch_ms,
        "dispatchGapMs": max(0.0, runs[-1] * 1000.0 - last_dispatch_ms),
        "dispatchAttribution": last_attr,
    }


def _numpy_reference_sgd(X, y, w, max_iter, batch, lr, tol):
    """The reference's exact SGD semantics (SGD.java:82-292 +
    TerminateOnMaxIterOrTol.java) in plain numpy: batch k = rows
    [k*B,(k+1)*B) cycling; first epoch computes the gradient on the init
    model before any update; one extra update after termination."""
    n, d = X.shape
    coeff = np.zeros(d, X.dtype)
    grad = np.zeros(d, X.dtype)
    wsum = 0.0
    loss = np.inf
    epoch = 0
    while epoch < max_iter and loss > tol:
        if wsum > 0:
            coeff = coeff - (lr / wsum) * grad
        k = epoch % max(1, -(-n // batch))
        sl = slice(k * batch, min((k + 1) * batch, n))
        Xk, yk, wk = X[sl], y[sl], w[sl]
        margin = (Xk @ coeff) * (2.0 * yk - 1.0)
        loss_sum = float(np.sum(wk * np.logaddexp(0.0, -margin)))
        mult = wk * (-(2.0 * yk - 1.0) / (np.exp(margin) + 1.0))
        grad = Xk.T @ mult
        wsum = float(np.sum(wk))
        loss = loss_sum / max(wsum, 1e-30)
        epoch += 1
    if wsum > 0:
        coeff = coeff - (lr / wsum) * grad
    return coeff, loss


def bench_loss_parity(num_rows=200_000):
    """Same small workload through the TPU engine and the numpy
    reference-semantics loop; losses must agree to f32 tolerance."""
    from flink_ml_tpu.models._linear import run_sgd  # noqa: F401  (engine import check)
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    rng = np.random.default_rng(7)
    X = rng.random((num_rows, DIM), dtype=np.float32)
    truth = rng.random(DIM, dtype=np.float32) - 0.5
    y = (X @ truth > 0).astype(np.float32)
    w = rng.random(num_rows, dtype=np.float32)

    sgd = SGD(
        max_iter=MAX_ITER,
        learning_rate=LR_RATE,
        global_batch_size=min(BATCH, num_rows),
        tol=TOL,
    )
    _, tpu_loss, _ = sgd.optimize(
        np.zeros(DIM, np.float32), X, y, w, BINARY_LOGISTIC_LOSS
    )
    _, ref_loss = _numpy_reference_sgd(
        X.astype(np.float64),
        y.astype(np.float64),
        w.astype(np.float64),
        MAX_ITER,
        min(BATCH, num_rows),
        LR_RATE,
        TOL,
    )
    rel = abs(tpu_loss - ref_loss) / max(abs(ref_loss), 1e-30)
    log(f"loss parity: tpu {tpu_loss:.6f} vs reference-semantics {ref_loss:.6f} (rel {rel:.2e})")
    return {"tpuLoss": tpu_loss, "referenceLoss": ref_loss, "relDiff": rel, "parity": rel < 1e-3}


def bench_cpu_baseline(num_rows):
    """CPU baseline for vs_baseline: the same job (datagen + reference-
    semantics SGD) in numpy on host — a stronger baseline than the
    reference's Flink job, making the reported speedup a lower bound."""
    t0 = time.perf_counter()
    rng = np.random.default_rng(2)
    X = rng.random((num_rows, DIM), dtype=np.float32)  # f32 direct: no 8GB f64 spike
    y = rng.integers(0, 2, size=num_rows).astype(np.float32)
    w = rng.random(num_rows, dtype=np.float32)
    _numpy_reference_sgd(X, y, w, MAX_ITER, min(BATCH, num_rows), LR_RATE, TOL)
    elapsed = time.perf_counter() - t0
    log(f"cpu baseline (numpy, same job): {elapsed * 1000:.0f} ms -> {num_rows / elapsed:.0f} records/s")
    return {"totalTimeMs": elapsed * 1000.0, "inputThroughput": num_rows / elapsed}


def bench_wide_sparse_lr(num_rows=1_000_000, dim=1_000_000, nnz=39):
    """The Criteo-style wide-model workload (SURVEY §2.3's TP motivation):
    LR at dim 1e6 over padded-CSR sparse rows (nnz=39 mirrors Criteo's 39
    features). Densified float32 this would be num_rows*dim*4 = 4TB — the
    sparse path holds (n, nnz) index/value arrays (~312MB) plus the (d,)
    model. Data is device-born like the headline workload; the dp x tp
    feature-sharded layout of the same engine is exercised by
    tests/test_sparse_training.py::TestShardedSparse and
    __graft_entry__.dryrun_multichip (one chip here, so no tp split to
    time)."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.ops.losses import SPARSE_BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    key = jax.random.PRNGKey(5)
    k1, k2, k3 = jax.random.split(key, 3)
    indices = jax.random.randint(k1, (num_rows, nnz), 0, dim, dtype=jnp.int32)
    values = jax.random.uniform(k2, (num_rows, nnz), dtype=jnp.float32)
    y = (jax.random.uniform(k3, (num_rows,)) > 0.5).astype(jnp.float32)
    sgd = SGD(
        max_iter=MAX_ITER,
        learning_rate=LR_RATE,
        global_batch_size=min(BATCH, num_rows),
        tol=TOL,
    )
    runs = []
    losses = []
    for i in range(3):  # run 0 = cold (compile)
        t0 = time.perf_counter()
        coeff, loss, epochs = sgd.optimize(
            np.zeros(dim, np.float32), (indices, values), y, None,
            SPARSE_BINARY_LOGISTIC_LOSS,
        )
        runs.append(time.perf_counter() - t0)
        losses.append(loss)
        log(
            f"wide sparse LR run {i}: fit {runs[-1] * 1000:.0f} ms, loss {loss:.6f}"
            + (" (cold: includes compile)" if i == 0 else "")
        )
    warm = min(runs[1:])
    return {
        "coldTimeMs": runs[0] * 1000.0,
        "totalTimeMs": warm * 1000.0,
        "inputRecordNum": num_rows,
        "dim": dim,
        "nnzPerRow": nnz,
        "inputThroughput": num_rows / warm,
        "finalLoss": float(losses[-1]),
        "densifiedBytesAvoided": float(num_rows) * dim * 4,
    }


def bench_sparse_2d_mesh(n=4096, dim=100_000, nnz=8, max_iter=8, batch_rows=1024):
    """The feature-sharded (data x feature) 2D-mesh workload (ISSUE 17,
    PAPER §2.3's beyond-HBM motivation): sparse LR with the coefficient
    AND the SGD grad carry living as model-axis slices while batches
    shard over data. Reports per-axis collective wire bytes (the SparCML
    pair exchange on `data`, active-feature assembly psums on `model`),
    per-shard carry residency vs the replicated layout (satellite:
    hbm.live.* reads ONE shard, never the sum across virtual hosts), the
    whole-fit ONE-dispatch contract on the 2D program, GSPMD-vs-2D
    coefficient agreement on the same mesh, and the admission
    acceptance: under a budget below one replicated f32 copy the 2D
    layout trains while replicated staging is refused with the typed
    HbmBudgetExceeded (docs/performance.md "2D mesh")."""
    import jax

    from flink_ml_tpu import config
    from flink_ml_tpu.obs import memledger
    from flink_ml_tpu.ops.losses import SPARSE_BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.parallel import collectives, overlap
    from flink_ml_tpu.parallel import mesh as mesh_lib
    from flink_ml_tpu.parallel import prefetch as h2d
    from flink_ml_tpu.utils import metrics

    n_dev = len(jax.devices())
    model_shards = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    rng = np.random.default_rng(17)
    indices = rng.integers(0, dim, size=(n, nnz)).astype(np.int32)
    values = rng.random((n, nnz))
    y = rng.integers(0, 2, size=n).astype(np.float64)
    init = np.zeros(dim)
    args = ((indices, values), y, None, SPARSE_BINARY_LOGISTIC_LOSS)

    def fit(mesh, sgd):
        with mesh_lib.use_mesh(mesh):
            return sgd.optimize(init, *args, mesh=mesh)

    def per_shard_bytes(mesh):
        # what the ledger sees for ONE staged carry under each layout —
        # per-device residency, not the sum across shards
        memledger.reset()
        staged = h2d.stage_to_device(
            np.zeros(dim, np.float32), mesh_lib.model_sharding(mesh),
            category="optimizer",
        )
        live = memledger.live_bytes("optimizer")
        del staged
        memledger.reset()
        return live

    mesh2d = mesh_lib.create_mesh_2d(model_shards)
    mesh1d = mesh_lib.create_mesh((mesh_lib.DATA_AXIS,))
    sgd = SGD(
        max_iter=max_iter, learning_rate=LR_RATE,
        global_batch_size=min(batch_rows, n), tol=0.0, shard_features=True,
    )

    # cold run: compile + trace-time per-axis wire accounting
    overlap.clear_program_cache()
    before = metrics.snapshot()
    t0 = time.perf_counter()
    fit(mesh2d, sgd)
    cold = time.perf_counter() - t0
    wire = collectives.axis_wire_bytes(
        metrics.snapshot_delta(before, metrics.snapshot())
    )

    # warm run: wall, dispatch count, peak residency
    memledger.reset()
    mark = memledger.mark_peak()
    before = metrics.snapshot()
    t0 = time.perf_counter()
    coeff, loss, epochs = fit(mesh2d, sgd)
    warm = time.perf_counter() - t0
    delta = metrics.snapshot_delta(before, metrics.snapshot())
    peak_2d = memledger.peak_since(mark)
    dispatches = int(delta["timers"].get("iteration.dispatch", {}).get("count", 0))
    assert dispatches == 1, f"2D whole fit paid {dispatches} dispatches"

    # replicated reference on the same devices (1D mesh: model_sharding
    # falls back to replication) — peak watermark + GSPMD agreement
    memledger.reset()
    mark = memledger.mark_peak()
    rep_coeff, _, rep_epochs = fit(mesh1d, sgd)
    peak_rep = memledger.peak_since(mark)
    memledger.reset()
    assert rep_epochs == epochs
    assert np.allclose(coeff, rep_coeff, rtol=3e-5, atol=3e-6), (
        "2D coefficients diverged from the replicated reference"
    )

    # admission acceptance: budget below ONE replicated f32 copy
    refused = 0.0
    if model_shards > 1:
        with config.hbm_budget_mode(3 * dim):
            fit(mesh2d, sgd)  # per-shard carries fit
            try:
                fit(mesh1d, sgd)
            except memledger.HbmBudgetExceeded:
                refused = 1.0
        memledger.reset()
        assert refused == 1.0, "replicated staging was not refused at budget"

    log(
        f"sparse2dMesh: ({n_dev // model_shards}x{model_shards}) mesh, dim {dim}: "
        f"fit {warm * 1000:.0f} ms ({dispatches} dispatch), wire "
        f"data {wire.get('data', 0)}B / model {wire.get('model', 0)}B, peak "
        f"{peak_2d}B vs replicated {peak_rep}B"
    )
    return {
        "inputRecordNum": n,
        "dim": dim,
        "nnzPerRow": nnz,
        "maxIter": max_iter,
        "dataShards": n_dev // model_shards,
        "modelShards": model_shards,
        "coldTimeMs": cold * 1000.0,
        "wallMs": warm * 1000.0,
        "trainedExamplesPerSec": min(batch_rows, n) * max_iter / warm,
        "finalLoss": float(loss),
        # gated lower-better leaves (scripts/bench_diff.py direction rules)
        "dispatchCount": dispatches,
        "dataAxisWireBytes": int(wire.get("data", 0)),
        "modelAxisWireBytes": int(wire.get("model", 0)),
        "peakHbmBytes": int(peak_2d),
        "optimizerPerShardBytes": int(per_shard_bytes(mesh2d)),
        # informational reference side (no direction: *Replicated)
        "peakHbmBytesReplicated": int(peak_rep),
        "optimizerBytesReplicated": int(per_shard_bytes(mesh1d)),
        "agreesWithGspmdReference": 1.0,  # asserted above
        "replicatedRefusedAtBudget": refused,
    }


def bench_kmeans():
    """The reference README's only published number (10k x dim 10, k=2)."""
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.table import Table

    rng = np.random.RandomState(2)
    X = rng.rand(10_000, 10)
    table = Table({"features": X})
    times = []
    for _ in range(3):  # min over warm runs smooths tunnel jitter
        start = time.perf_counter()
        model = KMeans().set_k(2).set_seed(2).fit(table)
        for t in model.get_model_data():
            t.collect()
        times.append(time.perf_counter() - start)
    warm = min(times[1:] or times)
    log(
        f"kmeans: warm {warm * 1000:.0f} ms, {10_000 / warm:.0f} records/s "
        f"(reference: 7148 ms, {BASELINE_KMEANS_THROUGHPUT:.0f} records/s)"
    )
    return {
        "coldTimeMs": times[0] * 1000.0,
        "totalTimeMs": warm * 1000.0,
        "inputThroughput": 10_000 / warm,
        "vsPublishedBaseline": 10_000 / warm / BASELINE_KMEANS_THROUGHPUT,
    }


def bench_pipeline_serving(num_batches=48, batch_rows=4096):
    """Serving-path workload (ISSUE 3): a 5-stage all-device feature
    pipeline driven over a micro-batch stream, fused+double-buffered
    (serving.MicroBatchServer) vs the eager per-stage transform loop.
    The contrast under measurement: eager pays one device program PLUS
    one blocking probe sync per guard stage per batch; fused pays one
    program and ONE packed drain per batch, with batch i+1's upload and
    compute overlapping batch i's drain. Outputs stay device-resident in
    both paths (a serving tier hands them to the next system; pulling
    them to host would time the caller's readback, not the pipeline)."""
    import jax

    from flink_ml_tpu import config
    from flink_ml_tpu.models.feature.binarizer import Binarizer
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler
    from flink_ml_tpu.pipeline import PipelineModel
    from flink_ml_tpu.serving import MicroBatchServer
    from flink_ml_tpu.table import StreamTable, Table
    from flink_ml_tpu.utils import metrics

    d_a, d_b = 64, 36
    rng = np.random.default_rng(3)
    scaler = StandardScalerModel()
    scaler.mean = rng.standard_normal(d_a + d_b)
    scaler.std = np.abs(rng.standard_normal(d_a + d_b)) + 0.1
    scaler.set_input_col("assembled").set_output_col("scaled")
    pipeline = PipelineModel(
        [
            VectorAssembler().set_input_cols("va", "vb").set_output_col("assembled"),
            scaler,
            Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
            Bucketizer()
            .set_input_cols("raw")
            .set_output_cols("bucket")
            .set_splits_array([[-1e6, -1.0, 0.0, 1.0, 1e6]]),
            Binarizer().set_input_cols("bucket").set_output_cols("bin").set_thresholds(1.5),
        ]
    )

    def make_batches():
        return [
            Table(
                {
                    "va": rng.standard_normal((batch_rows, d_a), dtype=np.float32),
                    "vb": rng.standard_normal((batch_rows, d_b), dtype=np.float32),
                    "raw": rng.standard_normal(batch_rows, dtype=np.float32),
                }
            )
            for _ in range(num_batches)
        ]

    def block_on(outputs):
        for t in outputs:
            jax.block_until_ready(
                [t.column(n) for n in ("norm", "bin") if n in t]
            )

    def run_fused(batches):
        server = MicroBatchServer(pipeline)
        before = metrics.snapshot()
        t0 = time.perf_counter()
        outs = list(server.serve(StreamTable.from_batches(batches)))
        block_on(outs[-1:])
        elapsed = time.perf_counter() - t0
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        return elapsed, delta

    def run_eager(batches):
        before = metrics.snapshot()
        t0 = time.perf_counter()
        outs = []
        with config.pipeline_fusion_mode("off"):
            for batch in batches:
                dev = Table(
                    {n: jax.device_put(batch.column(n)) for n in batch.column_names}
                )
                outs.append(pipeline.transform(dev)[0])
        block_on(outs[-1:])
        elapsed = time.perf_counter() - t0
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        return elapsed, delta

    records = num_batches * batch_rows
    run_fused(make_batches()[:2])  # compile warmup, both bucket + plan
    run_eager(make_batches()[:2])
    # min over repeats smooths scheduler jitter (the per-batch cost is
    # milliseconds, well inside CPU-host noise); interleaved so neither
    # path systematically benefits from a warmer cache
    fused_s, fused_delta = run_fused(make_batches())
    eager_s, eager_delta = run_eager(make_batches())
    for _ in range(2):
        s, d = run_fused(make_batches())
        if s < fused_s:
            fused_s, fused_delta = s, d
        s, d = run_eager(make_batches())
        if s < eager_s:
            eager_s, eager_delta = s, d
    fused_syncs = fused_delta["counters"].get("iteration.host_sync.transform", 0)
    eager_syncs = eager_delta["counters"].get("iteration.host_sync.transform", 0)
    result = {
        "numBatches": num_batches,
        "batchRows": batch_rows,
        "numStages": len(pipeline.stages),
        "inputRecordNum": records,
        "fusedRecordsPerSec": records / fused_s,
        "eagerRecordsPerSec": records / eager_s,
        "speedup": eager_s / fused_s,
        "fusedTimeMs": fused_s * 1000.0,
        "eagerTimeMs": eager_s * 1000.0,
        # first-class dispatch evidence: fused syncs once per batch no
        # matter the stage count; eager syncs once per guard stage per batch
        "hostSyncCount": int(fused_syncs),
        "hostSyncCountEager": int(eager_syncs),
        "hostSyncsPerBatch": fused_syncs / num_batches,
        "hostSyncsPerBatchEager": eager_syncs / num_batches,
        "fusedSegments": int(fused_delta["gauges"].get("pipeline.fused_segments", 0)),
        "servingInFlight": int(fused_delta["gauges"].get("serving.in_flight", 0)),
    }
    log(
        f"pipelineServing: fused {result['fusedRecordsPerSec']:.0f} rec/s vs eager "
        f"{result['eagerRecordsPerSec']:.0f} rec/s ({result['speedup']:.2f}x), "
        f"syncs/batch {result['hostSyncsPerBatch']:.1f} vs {result['hostSyncsPerBatchEager']:.1f}, "
        f"{result['fusedSegments']} fused segment(s) of {result['numStages']} stages"
    )
    return result


def bench_input_pipeline(num_batches=8, batch_rows=20_000, d=64, epochs=6):
    """The input-layer workload (ISSUE 5): a bounded stream fit replayed
    over `epochs` passes, device-epoch-cached vs eager re-upload
    (`config.device_cache_bytes` None vs 0). The claims under measurement:
    epochs >= 1 of the cached path move ZERO host→device bytes (the
    `h2d.bytes` counter, asserted in-process), both paths produce
    bit-identical coefficients, and bucketed staging compiles fewer
    programs than exact-shape staging on a ragged KMeans stream."""
    from flink_ml_tpu import config
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.obs import tracing
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.table import StreamTable, Table
    from flink_ml_tpu.utils import metrics

    tracing.install_jax_hooks()
    n = num_batches * batch_rows
    rng = np.random.default_rng(9)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)
    max_iter = epochs * num_batches  # full passes over the cached stream

    def chunks():
        return iter(
            [
                (X[i : i + batch_rows], y[i : i + batch_rows], None)
                for i in range(0, n, batch_rows)
            ]
        )

    def run(budget):
        # whole_fit off: this entry measures the per-epoch replay pipeline
        # (cache vs eager re-upload); the resident path bypasses it and
        # has its own wholeFitDispatch entry
        with config.whole_fit_mode("off"), config.device_cache_budget(budget):
            sgd = SGD(max_iter=max_iter, global_batch_size=batch_rows, tol=0.0)
            before = metrics.snapshot()
            t0 = time.perf_counter()
            coeff, _, _, _ = sgd.optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)
            wall = time.perf_counter() - t0
            delta = metrics.snapshot_delta(before, metrics.snapshot())
        return coeff, wall, delta["counters"]

    run(None)  # compile warmup for both kernels
    cached_coeff, cached_wall, cached_c = run(None)
    eager_coeff, eager_wall, eager_c = run(0)
    cached_bytes = cached_c.get("h2d.bytes", 0)
    eager_bytes = eager_c.get("h2d.bytes", 0)
    epoch0_bytes = eager_bytes / epochs  # eager re-uploads every pass alike
    later_epochs_bytes = (cached_bytes - epoch0_bytes) / max(1, epochs - 1)
    assert np.array_equal(cached_coeff, eager_coeff), (
        "cached epochs diverged from the eager re-upload path"
    )

    # bucketed vs unbucketed compile counts on a deliberately ragged
    # KMeans stream (the micro-batch-jitter recompile story). Each mode
    # is measured at its own feature dim after a uniform-batch warmup fit
    # at that dim, so the counted compiles are exactly the ones the
    # jittered batch SHAPES caused — not shared first-fit warmup.
    rng_k = np.random.default_rng(10)
    sizes = [257, 511, 383, 640, 333, 476, 600]
    offs = np.cumsum([0] + sizes)

    def compile_cost(bucketing, dim):
        Xk = rng_k.standard_normal((offs[-1], dim)).astype(np.float32)
        uniform = [
            Table({"features": Xk[i : i + 512]}) for i in range(0, 1024, 512)
        ]
        ragged = [
            Table({"features": Xk[offs[i] : offs[i + 1]]})
            for i in range(len(sizes))
        ]
        kfit = lambda b: KMeans().set_k(4).set_seed(3).set_max_iter(2).fit(  # noqa: E731
            StreamTable.from_batches(b)
        )
        with config.whole_fit_mode("off"), config.input_bucketing_mode(bucketing):
            kfit(uniform)  # warm every kernel at the uniform batch shape
            before = metrics.get_counter("jit.compiles")
            kfit(ragged)
            return metrics.get_counter("jit.compiles") - before

    compiles_bucketed = compile_cost(True, 16)
    compiles_unbucketed = compile_cost(False, 17)

    result = {
        "numBatches": num_batches,
        "batchRows": batch_rows,
        "dim": d,
        "epochs": epochs,
        "cachedWallMs": cached_wall * 1000.0,
        "eagerWallMs": eager_wall * 1000.0,
        "cachedEpochWallMs": cached_wall * 1000.0 / epochs,
        "eagerEpochWallMs": eager_wall * 1000.0 / epochs,
        "speedup": eager_wall / cached_wall,
        # the acceptance number: host→device bytes per epoch after epoch 0
        # on the cached path — 0 within budget
        "h2dBytesPerEpochCached": later_epochs_bytes,
        "h2dBytesPerEpochEager": epoch0_bytes,
        "h2dBytesCachedTotal": cached_bytes,
        "h2dBytesEagerTotal": eager_bytes,
        "deviceCacheHits": int(cached_c.get("devicecache.hit", 0)),
        "bitIdenticalToEager": True,  # asserted above
        "raggedStreamCompilesBucketed": int(compiles_bucketed),
        "raggedStreamCompilesUnbucketed": int(compiles_unbucketed),
    }
    log(
        f"inputPipeline: cached epoch {result['cachedEpochWallMs']:.1f}ms vs eager "
        f"{result['eagerEpochWallMs']:.1f}ms ({result['speedup']:.2f}x), "
        f"H2D/epoch cached {later_epochs_bytes / 1e6:.2f}MB vs eager "
        f"{epoch0_bytes / 1e6:.2f}MB; ragged-stream compiles bucketed "
        f"{compiles_bucketed} vs unbucketed {compiles_unbucketed}"
    )
    return result


def bench_whole_fit_dispatch(n=400_000, d=32, max_iter=200, batch_rows=4096):
    """The whole-fit resident-program workload (ISSUE 13 / ROADMAP item
    2a): the SAME maxIter=200 out-of-core LR fit on the per-epoch dispatch
    pipeline (`config.whole_fit` off — one dispatch + one drained readback
    PER EPOCH) vs the resident program (one dispatch + one packed readback
    PER FIT). Reports the dispatch count (`iteration.dispatch` launches),
    `hostSyncCount`, host-dispatch wall and the flight-recorder
    attribution for both sides, asserts bit-identical coefficients
    in-process, and derives the trace-MFU proxy delta: with fixed device
    work per fit, MFU scales as 1/wall, so the wall ratio IS the MFU lift
    on this workload."""
    from flink_ml_tpu import config
    from flink_ml_tpu.obs import timeline
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(23)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)

    def chunks():
        return iter(
            [
                (X[i : i + batch_rows], y[i : i + batch_rows], None)
                for i in range(0, n, batch_rows)
            ]
        )

    def run(mode):
        with config.whole_fit_mode(mode):
            sgd = SGD(max_iter=max_iter, global_batch_size=batch_rows, tol=0.0)
            sgd.optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)  # warm
            timeline.configure(ring_size=65536)
            mark_us = timeline.now_us()
            before = metrics.snapshot()
            t0 = time.perf_counter()
            coeff, _, epochs, _ = sgd.optimize_stream(
                None, chunks(), BINARY_LOGISTIC_LOSS
            )
            wall = time.perf_counter() - t0
            delta = metrics.snapshot_delta(before, metrics.snapshot())
            events, _ = timeline.snapshot_events()
            attr = timeline.dispatch_attribution(
                [e for e in events if e["tsUs"] >= mark_us]
            )
            timeline.configure()
            if attr:
                attr.pop("chunks", None)
        return {
            "coeff": coeff,
            "epochs": epochs,
            "wallMs": wall * 1000.0,
            "hostSyncCount": int(delta["counters"].get("iteration.host_sync", 0)),
            "dispatchCount": int(
                delta["timers"].get("iteration.dispatch", {}).get("count", 0)
            ),
            "hostDispatchMs": float(
                delta["timers"].get("iteration.dispatch", {}).get("totalMs", 0.0)
            ),
            "wholeFitCount": int(delta["counters"].get("dispatch.whole_fit", 0)),
            "wholeFitFallbacks": int(
                delta["counters"].get("dispatch.whole_fit_fallback", 0)
            ),
            "attribution": attr,
        }

    chunked = run("off")
    whole = run("auto")
    assert np.array_equal(chunked["coeff"], whole["coeff"]), (
        "whole-fit diverged from the chunked reference"
    )
    assert whole["hostSyncCount"] == 1, (
        f"whole-fit paid {whole['hostSyncCount']} host syncs, expected 1"
    )
    examples = min(batch_rows, n) * max_iter
    result = {
        "maxIter": max_iter,
        "inputRecordNum": n,
        "dim": d,
        # gated side: the resident program (lower-better leaves)
        "wallMs": whole["wallMs"],
        "hostSyncCount": whole["hostSyncCount"],
        "dispatchCount": whole["dispatchCount"],
        "hostDispatchMs": whole["hostDispatchMs"],
        "trainedExamplesPerSec": examples / (whole["wallMs"] / 1000.0),
        "wholeFitFallbacks": whole["wholeFitFallbacks"],
        "dispatchAttribution": whole["attribution"],
        # reference side (informational leaves: *Chunked has no direction)
        "wallMsChunked": chunked["wallMs"],
        "hostSyncCountChunked": chunked["hostSyncCount"],
        "dispatchCountChunked": chunked["dispatchCount"],
        "hostDispatchMsChunked": chunked["hostDispatchMs"],
        "dispatchAttributionChunked": chunked["attribution"],
        # fixed device work per fit => MFU ~ 1/wall: the wall ratio is
        # the trace-MFU lift of going resident on this workload
        "mfuProxyLift": chunked["wallMs"] / whole["wallMs"],
        "dispatchReduction": (
            chunked["dispatchCount"] / max(1, whole["dispatchCount"])
        ),
        "bitIdenticalToChunked": True,  # asserted above
    }
    log(
        f"wholeFitDispatch: {chunked['dispatchCount']} dispatches/"
        f"{chunked['hostSyncCount']} syncs -> {whole['dispatchCount']}/"
        f"{whole['hostSyncCount']} at maxIter={max_iter}; wall "
        f"{chunked['wallMs']:.0f}ms -> {whole['wallMs']:.0f}ms "
        f"({result['mfuProxyLift']:.2f}x MFU proxy), hostDispatch "
        f"{whole['hostDispatchMs']:.1f}ms of {whole['wallMs']:.0f}ms wall"
    )
    return result


def bench_fleet_sweep(
    n=100_000,
    d=32,
    max_iter=12,
    batch_rows=4096,
    fleet_sizes=(1, 32, 512),
    in_budget=lambda: True,
):
    """The FitFleet many-model workload (docs/performance.md §11): the
    SAME LR fit swept over per-member learning rates, trained as ONE
    vmapped resident dispatch at each fleet size. Reports models/s and
    trained-examples/s at N in {1, 32, 512}; the N=32 point asserts the
    amortization contract in-process — ONE dispatch, ONE blocking host
    sync for the whole fleet — and every member's coefficients
    bit-identical to its solo whole-fit run. The gated leaves
    (dispatchCount / hostSyncCount / modelsPerSecond /
    trainedExamplesPerSec) come from that N=32 point."""
    from flink_ml_tpu.fleet import FitFleet
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )
    from flink_ml_tpu.table import Table
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(29)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)
    table = Table({"features": X, "label": y})

    def member(i, size):
        # a real sweep: every member trains a distinct hyper point
        return (
            LogisticRegression()
            .set_max_iter(max_iter)
            .set_tol(0.0)
            .set_learning_rate(0.05 * (1.0 + i / max(1, size)))
            .set_global_batch_size(batch_rows)
        )

    def run(size):
        fleet = FitFleet([member(i, size) for i in range(size)])
        fleet.fit(table)  # warm: compile the size-N program off the clock
        before = metrics.snapshot()
        t0 = time.perf_counter()
        models = FitFleet([member(i, size) for i in range(size)]).fit(table)
        wall = time.perf_counter() - t0
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        examples = int(delta["counters"].get("fleet.examplesTrained", 0))
        return models, {
            "fleetSize": size,
            "wallMs": wall * 1000.0,
            "modelsPerSecond": size / wall,
            "trainedExamplesPerSec": examples / wall,
            "dispatchCount": int(
                delta["timers"].get("iteration.dispatch", {}).get("count", 0)
            ),
            "hostSyncCount": int(delta["counters"].get("iteration.host_sync", 0)),
            "wholeFitFleetCount": int(
                delta["counters"].get("dispatch.whole_fit.fleet", 0)
            ),
        }

    # the gate point: N=32 when swept, else the largest size that runs —
    # so smoke-scale sweeps still pin the bit-identity contract in-process
    gate_size = (
        32
        if 32 in fleet_sizes
        else max((s for s in fleet_sizes if s <= 32), default=min(fleet_sizes))
    )
    by_size = {}
    gate_models = None
    for size in fleet_sizes:
        if size > 32 and not in_budget():
            log(f"fleetSweep: skipping N={size} (budget)")
            continue
        models, point = run(size)
        by_size[str(size)] = point
        if size == gate_size:
            gate_models = models
        log(
            f"fleetSweep N={size}: {point['modelsPerSecond']:.1f} models/s, "
            f"{point['trainedExamplesPerSec']:.3g} examples/s, "
            f"{point['dispatchCount']} dispatch / {point['hostSyncCount']} sync "
            f"in {point['wallMs']:.0f}ms"
        )

    gate = by_size[str(gate_size)]
    assert gate["dispatchCount"] == 1, (
        f"fleet fit paid {gate['dispatchCount']} dispatches, expected 1"
    )
    assert gate["hostSyncCount"] == 1, (
        f"fleet fit paid {gate['hostSyncCount']} host syncs, expected 1"
    )
    if gate_models is not None:
        # every member vs its solo whole-fit run — bit-identical
        for i, model in enumerate(gate_models):
            solo = member(i, gate_size).fit(table)
            assert np.array_equal(
                np.asarray(model.coefficient), np.asarray(solo.coefficient)
            ), f"fleet member {i} diverged from its solo fit"

    result = {
        "inputRecordNum": n,
        "dim": d,
        "maxIter": max_iter,
        # gated leaves: the N=32 amortization point (lower-better counts,
        # higher-better throughputs — bench_diff direction rules)
        "dispatchCount": gate["dispatchCount"],
        "hostSyncCount": gate["hostSyncCount"],
        "wallMs": gate["wallMs"],
        "modelsPerSecond": gate["modelsPerSecond"],
        "trainedExamplesPerSec": gate["trainedExamplesPerSec"],
        "bitIdenticalToSolo": gate_models is not None,  # asserted above
        "byFleetSize": by_size,
    }
    if "1" in by_size and "32" in by_size:
        # the headline amortization ratio: models/s lift of batching 32
        # fits into one program vs training them one at a time
        result["modelsPerSecondLift32"] = (
            by_size["32"]["modelsPerSecond"] / by_size["1"]["modelsPerSecond"]
        )
    return result


def bench_checkpoint_resume(n=200_000, d=64, max_iter=24, kill_after_chunks=8):
    """The preemption-safety workload (ISSUE 6): dense SGD with JobSnapshot
    checkpointing every epoch. Reports (a) snapshot cost — wall delta per
    epoch vs the same fit without checkpointing, plus the checkpoint.bytes/
    count the run actually wrote; (b) resume-to-first-step wall — restore
    the snapshot and advance ONE epoch (the recovery-latency number: how
    long after a preemption the job is training again); (c) bit-identity —
    a fit killed mid-training by the fault harness and resumed must land on
    the uninterrupted run's exact coefficients (asserted in-process)."""
    import shutil
    import tempfile

    from flink_ml_tpu.ckpt import InjectedFault, faults
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(17)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)
    B = 20_000

    def fit(ckpt_dir=None, max_iter=max_iter):
        sgd = SGD(
            max_iter=max_iter, global_batch_size=B, tol=0.0,
            checkpoint_dir=ckpt_dir, checkpoint_interval=1,
            checkpoint_key="checkpointResume",  # namespaced: no un-keyed warning
        )
        t0 = time.perf_counter()
        coeff, _, epochs = sgd.optimize(
            np.zeros(d, np.float32), X, y, None, BINARY_LOGISTIC_LOSS
        )
        return coeff, epochs, time.perf_counter() - t0

    work = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        fit()  # compile warmup (both the plain and chunked programs)
        fit(os.path.join(work, "warm"))
        _, _, plain_wall = fit()
        before = metrics.snapshot()
        # the uninterrupted reference for the bit-identity assert runs the
        # SAME checkpointed (chunked) program as the killed fit — the flat
        # single-shard path is a different batch layout (allclose, not
        # bit-equal, to the batched one)
        expected, _, ckpt_wall = fit(os.path.join(work, "cadence"))
        delta = metrics.snapshot_delta(before, metrics.snapshot())["counters"]
        save_count = int(delta.get("checkpoint.count", 0))
        save_bytes = int(delta.get("checkpoint.bytes", 0))

        # kill mid-training at a chunk boundary, then resume to completion
        kill_dir = os.path.join(work, "kill")
        killed_at = None
        try:
            with faults.inject("chunk", after=kill_after_chunks):
                fit(kill_dir)
        except InjectedFault as e:
            killed_at = e.hits
        assert killed_at is not None, "fault never fired — raise max_iter"
        resumed, epochs, resume_wall = fit(kill_dir)
        bit_identical = bool(np.array_equal(np.asarray(resumed), np.asarray(expected)))
        assert bit_identical, "kill -> resume diverged from the uninterrupted fit"

        # recovery latency: restore the snapshot and advance ONE epoch
        first_dir = os.path.join(work, "first")
        try:
            with faults.inject("chunk", after=kill_after_chunks):
                fit(first_dir)
        except InjectedFault:
            pass
        t0 = time.perf_counter()
        _, first_epochs, _ = fit(first_dir, max_iter=kill_after_chunks + 1)
        resume_to_first_step = time.perf_counter() - t0
    finally:
        shutil.rmtree(work, ignore_errors=True)

    result = {
        "numRows": n,
        "dim": d,
        "maxIter": max_iter,
        "plainWallMs": plain_wall * 1000.0,
        "checkpointedWallMs": ckpt_wall * 1000.0,
        "saveMsPerEpoch": (ckpt_wall - plain_wall) * 1000.0 / max_iter,
        "checkpointCount": save_count,
        "checkpointBytes": save_bytes,
        "checkpointBytesPerSave": save_bytes / max(1, save_count),
        "killedAtChunk": killed_at,
        "resumeWallMs": resume_wall * 1000.0,
        "resumeToFirstStepMs": resume_to_first_step * 1000.0,
        "resumedEpochs": int(epochs),
        "bitIdenticalToUninterrupted": bit_identical,  # asserted above
    }
    log(
        f"checkpointResume: save {result['saveMsPerEpoch']:.2f}ms/epoch "
        f"({result['checkpointBytesPerSave'] / 1e3:.1f}KB/save, "
        f"{save_count} saves), kill@chunk {killed_at} -> resume-to-first-step "
        f"{result['resumeToFirstStepMs']:.1f}ms, bit-identical resume"
    )
    return result


def bench_multihost_checkpoint(
    n=200_000, d=64, max_iter=12, host_counts=(1, 4, 8), kill_after=6
):
    """Multi-host snapshot workload (ISSUE 14): dense SGD checkpointing
    every epoch through the sharded two-phase-commit coordinator
    (ckpt/coordinator.py) at several simulated host counts. Reports per
    host count: (a) save wall per epoch (wall delta vs the same fit
    without checkpointing) and shard bytes per host — the scaling curve
    of the per-host write path; (b) kill@manifest-commit -> resume wall
    (the recovery number for a cut torn exactly at the two-phase-commit
    window); (c) bit-identity — the killed+resumed sharded fit must land
    on the single-file path's exact coefficients (asserted in-process:
    the snapshot transport changes WHERE bytes live, never the model)."""
    import shutil
    import tempfile

    from flink_ml_tpu import config as _config
    from flink_ml_tpu.ckpt import InjectedFault, faults
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(23)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)

    def fit(ckpt_dir=None, max_iter=max_iter):
        sgd = SGD(
            max_iter=max_iter, global_batch_size=20_000, tol=0.0,
            checkpoint_dir=ckpt_dir, checkpoint_interval=1,
            checkpoint_key="multiHostCheckpoint",
        )
        t0 = time.perf_counter()
        coeff, _, epochs = sgd.optimize(
            np.zeros(d, np.float32), X, y, None, BINARY_LOGISTIC_LOSS
        )
        return coeff, epochs, time.perf_counter() - t0

    work = tempfile.mkdtemp(prefix="bench_mh_ckpt_")
    per_hosts = {}
    try:
        fit()  # compile warmup
        fit(os.path.join(work, "warm"))
        _, _, plain_wall = fit()
        expected, _, _ = fit(os.path.join(work, "single"))  # single-file ref

        for hosts in host_counts:
            with _config.snapshot_hosts_mode(hosts):
                before = metrics.snapshot()
                _, _, wall = fit(os.path.join(work, f"h{hosts}"))
                delta = metrics.snapshot_delta(before, metrics.snapshot())[
                    "counters"
                ]
            shard_bytes = int(delta.get("checkpoint.shard.bytes", 0))
            shard_count = int(delta.get("checkpoint.shard.count", 0))
            saves = int(delta.get("checkpoint.manifest.count", 0))
            per_hosts[f"host{hosts}"] = {
                "wallMs": wall * 1000.0,
                "savePerEpochMs": (wall - plain_wall) * 1000.0 / max_iter,
                "shardBytesPerHost": shard_bytes / max(1, saves * hosts),
                "shardFilesPerSave": shard_count / max(1, saves),
                "manifestCommits": saves,
            }

        # kill exactly inside the two-phase-commit window (shards landed,
        # manifest rename never ran), then resume elastically onto a
        # DIFFERENT simulated host count
        kill_dir = os.path.join(work, "kill")
        killed_at = None
        with _config.snapshot_hosts_mode(host_counts[-1]):
            try:
                with faults.inject("snapshot.commit", after=kill_after):
                    fit(kill_dir)
            except InjectedFault as e:
                killed_at = e.hits
        assert killed_at is not None, "commit fault never fired"
        with _config.snapshot_hosts_mode(host_counts[0]):
            t0 = time.perf_counter()
            resumed, epochs, _ = fit(kill_dir)
            resume_wall = time.perf_counter() - t0
        bit_identical = bool(
            np.array_equal(np.asarray(resumed), np.asarray(expected))
        )
        assert bit_identical, (
            "sharded kill@commit -> resume diverged from the single-file fit"
        )
    finally:
        shutil.rmtree(work, ignore_errors=True)

    result = {
        "numRows": n,
        "dim": d,
        "maxIter": max_iter,
        "plainWallMs": plain_wall * 1000.0,
        **per_hosts,
        "killedAtCommit": killed_at,
        "resumeWallMs": resume_wall * 1000.0,
        "resumedEpochs": int(epochs),
        "bitIdenticalToSingleFile": bit_identical,  # asserted above
    }
    biggest = per_hosts[f"host{host_counts[-1]}"]
    log(
        f"multiHostCheckpoint: {host_counts[-1]} hosts save "
        f"{biggest['savePerEpochMs']:.2f}ms/epoch "
        f"({biggest['shardBytesPerHost'] / 1e3:.1f}KB/host/save), "
        f"kill@commit {killed_at} -> resume {resume_wall * 1000.0:.1f}ms, "
        "bit-identical to the single-file path"
    )
    return result


def bench_elastic_recovery(n=100_000, d=32, max_iter=12, hosts=4):
    """Elastic-supervisor workload (ISSUE 15): a checkpointed dense SGD
    fit under `parallel/supervisor.supervise` with sharded snapshots,
    chaos-injected twice: (a) a collective HANG mid-drain — detected by
    the dispatch-progress deadline, host readmitted, SAME-host-count
    resume asserted BIT-IDENTICAL to the unkilled fit; (b) a host DEATH
    mid-epoch — detected by heartbeat timeout, host quarantined, mesh
    re-formed over survivors, cross-count resume asserted allclose per
    the reduction-order caveat. Reports per scenario: detection latency
    (fault observable -> monitor detected) and recovery wall (detected ->
    resumed fit's first progress); top-level detectionMs/recoveryWallMs
    are the worst of the two (the conservative SLO numbers the CI
    bench_diff rules gate)."""
    import shutil
    import tempfile

    from flink_ml_tpu import config as _config
    from flink_ml_tpu.ckpt import faults
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.parallel import supervisor

    rng = np.random.default_rng(31)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)

    def make_fit(ckpt_dir):
        def fit(mesh):
            return SGD(
                max_iter=max_iter, global_batch_size=20_000, tol=0.0,
                checkpoint_dir=ckpt_dir, checkpoint_interval=1,
                checkpoint_key="elasticRecovery",
            ).optimize(
                np.zeros(d, np.float32), X, y, None,
                BINARY_LOGISTIC_LOSS, mesh=mesh,
            )

        return fit

    work = tempfile.mkdtemp(prefix="bench_elastic_")
    scenarios = {}
    try:
        from flink_ml_tpu.parallel import mesh as mesh_lib

        expected, _, _ = make_fit(os.path.join(work, "ref"))(
            mesh_lib.default_mesh()
        )
        expected = np.asarray(expected)

        with _config.snapshot_hosts_mode(hosts):
            # (a) collective hang mid-drain: readmit, bit-identical resume
            hang_dir = os.path.join(work, "hang")
            with faults.inject("host.hang.collective", after=3):
                t0 = time.perf_counter()
                res = supervisor.supervise(
                    make_fit(hang_dir), hosts=hosts,
                    checkpoint_dir=hang_dir, job_key="elasticRecovery",
                    heartbeat_timeout_s=30.0, poll_interval_s=0.005,
                )
                hang_wall = time.perf_counter() - t0
            assert res.recoveries == 1 and res.hosts == hosts
            (ev,) = res.events
            assert ev.kind == "collectiveHang"
            coeff, _, epochs = res.value
            assert epochs == max_iter
            assert np.array_equal(np.asarray(coeff), expected), (
                "same-host-count elastic resume diverged from the unkilled fit"
            )
            scenarios["hang"] = {
                "detectionMs": ev.detection_ms,
                "recoveryWallMs": ev.recovery_ms,
                "supervisedWallMs": hang_wall * 1000.0,
                "hostsAfter": res.hosts,
                "bitIdentical": True,  # asserted above
            }

            # (b) host death mid-epoch: quarantine + shrink, allclose resume
            die_dir = os.path.join(work, "die")
            with faults.inject("host.die.dispatch", after=3):
                t0 = time.perf_counter()
                res = supervisor.supervise(
                    make_fit(die_dir), hosts=hosts,
                    checkpoint_dir=die_dir, job_key="elasticRecovery",
                    heartbeat_timeout_s=0.25, poll_interval_s=0.005,
                )
                die_wall = time.perf_counter() - t0
            assert res.recoveries == 1 and res.hosts == hosts - 1
            (ev,) = res.events
            assert ev.kind == "hostFailure" and ev.quarantined
            coeff, _, epochs = res.value
            assert epochs == max_iter
            assert np.allclose(np.asarray(coeff), expected, rtol=5e-4, atol=1e-6), (
                "shrink resume diverged beyond the reduction-order envelope"
            )
            scenarios["hostDeath"] = {
                "detectionMs": ev.detection_ms,
                "recoveryWallMs": ev.recovery_ms,
                "supervisedWallMs": die_wall * 1000.0,
                "hostsAfter": res.hosts,
                "allclose": True,  # asserted above
            }
    finally:
        shutil.rmtree(work, ignore_errors=True)

    result = {
        "numRows": n,
        "dim": d,
        "maxIter": max_iter,
        "hosts": hosts,
        **scenarios,
        "detectionMs": max(s["detectionMs"] for s in scenarios.values()),
        "recoveryWallMs": max(
            s["recoveryWallMs"] or 0.0 for s in scenarios.values()
        ),
        "parityAsserted": True,
    }
    log(
        f"elasticRecovery: hang detected {scenarios['hang']['detectionMs']:.0f}ms"
        f" / recovered {scenarios['hang']['recoveryWallMs']:.0f}ms"
        " (bit-identical resume), host death detected "
        f"{scenarios['hostDeath']['detectionMs']:.0f}ms / recovered "
        f"{scenarios['hostDeath']['recoveryWallMs']:.0f}ms "
        f"({hosts}->{hosts - 1} hosts, allclose)"
    )
    return result


def bench_overload_soak(num_requests=60, batch_rows=256, d=24):
    """Robustness workload (ISSUE 8): bursty producer x slow/flaky
    consumer, asserted in-process:

    1. **Overloaded serving sheds at the door with bounded memory** — an
       unpaced producer fires `num_requests` submits at a MicroBatchServer
       with a small admission queue + in-flight window. The reject policy
       must fast-fail (ServerOverloaded) instead of queueing, both queue
       depths must peak within their configured capacities (the bounded-
       peak-memory claim, reported in bytes), every admitted request must
       retire, and the dispatch worker must exit — zero deadlock,
       enforced by a bounded join.
    2. **shed_oldest bounds model staleness** — a producer bursts 40x the
       channel capacity between consumer gets; consumed lag must stay
       BELOW the capacity while sheds are counted (the staleness contract
       of docs/flow_control.md).
    3. **Transient-fault retries are result-invisible** — one stream-SGD
       fit runs clean, then again with a flaky spill-read fault under the
       retry budget (bit-identical coefficients required, retries proven
       by the fault plan AND the flow.retry counter), then again with the
       budget at 0 (the same fault must now be fatal).
    """
    import jax

    from flink_ml_tpu import config, flow
    from flink_ml_tpu.ckpt import faults
    from flink_ml_tpu.ckpt.faults import TransientFault
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.pipeline import PipelineModel
    from flink_ml_tpu.serving import MicroBatchServer, ServerOverloaded
    from flink_ml_tpu.table import Table
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(17)
    t_start = time.perf_counter()

    # -- 1. serving under burst: reject at the door, bounded queues --------
    scaler = StandardScalerModel()
    scaler.mean = rng.standard_normal(d)
    scaler.std = np.abs(rng.standard_normal(d)) + 0.1
    scaler.set_input_col("features").set_output_col("scaled")
    pipeline = PipelineModel(
        [scaler, Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm")]
    )
    server = MicroBatchServer(pipeline, in_flight=2, admission=4)
    batch_nbytes = batch_rows * d * 4
    submitted = rejected = 0
    for _ in range(num_requests):
        try:
            server.submit(Table({"features": rng.standard_normal((batch_rows, d), dtype=np.float32)}))
            submitted += 1
        except ServerOverloaded as e:
            assert e.depth <= e.capacity, "reject must fire AT capacity, not past it"
            rejected += 1
    server.close()
    results = list(server.results())
    server._worker.join(timeout=120.0)
    assert not server._worker.is_alive(), "dispatch worker wedged: deadlock"
    health = server.health()
    assert submitted + rejected == num_requests
    assert len(results) == submitted, "every admitted request must retire"
    assert all(r.status == "ok" for r in results)
    peak_admit = server._requests.stats.peak_depth
    peak_window = server._window.stats.peak_depth
    assert peak_admit <= server.admission, "admission queue exceeded its bound"
    assert peak_window <= server.in_flight, "in-flight window exceeded its bound"
    jax.block_until_ready(
        [results[-1].table.column("norm")] if results else []
    )
    # deadline leg on a fresh server: a request whose deadline passed
    # before dispatch is shed WITHOUT paying staging or compute
    expiry_server = MicroBatchServer(pipeline, in_flight=2, admission=8)
    expired_submits = 0
    for _ in range(5):
        try:
            expiry_server.submit(
                Table({"features": rng.standard_normal((batch_rows, d), dtype=np.float32)}),
                deadline_ms=0.0,
            )
            expired_submits += 1
        except ServerOverloaded:
            pass
    expiry_server.close()
    expiry_results = list(expiry_server.results())
    assert len(expiry_results) == expired_submits
    expired = sum(1 for r in expiry_results if r.status in ("expired", "late"))
    assert expired == expired_submits, "0ms-deadline requests must be shed/late"

    # -- 2. shed_oldest staleness bound ------------------------------------
    capacity = 4
    chan = flow.BoundedChannel(capacity, policy=flow.SHED_OLDEST, name="soak.online")
    produced = 0
    for round_ in range(10):
        for _ in range(capacity * 40):  # the burst: 40x capacity per get
            chan.put(produced)
            produced += 1
        chan.get()  # the slow consumer folds one item per burst
    assert chan.stats.shed > 0, "the burst must actually shed"
    assert chan.stats.max_lag < capacity, (
        f"staleness contract broken: lag {chan.stats.max_lag} >= capacity {capacity}"
    )

    # -- 3. retries on vs off: bit-identical or fatal ----------------------
    X = rng.standard_normal((480, 16)).astype(np.float32)
    y = (X @ rng.standard_normal(16).astype(np.float32) > 0).astype(np.float32)

    def chunks():
        return iter([(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)])

    def fit():
        sgd = SGD(max_iter=6, global_batch_size=100, tol=0.0)
        return sgd.optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)

    clean, _, _, _ = fit()
    retry_before = metrics.get_counter("flow.retry", 0)
    with config.transient_retry_mode(4):
        with faults.flaky("datacache.read", times=3) as plan:
            retried, _, _, _ = fit()
    retries_paid = metrics.get_counter("flow.retry", 0) - retry_before
    assert plan.failures == 3, "the flaky plan must actually fire"
    assert retries_paid >= 3, "retries must ride flow.with_retries (counted)"
    assert np.array_equal(np.asarray(clean), np.asarray(retried)), (
        "transient-fault retries changed the training result"
    )
    fatal = False
    with config.transient_retry_mode(0):
        with faults.flaky("datacache.read", times=1):
            try:
                fit()
            except TransientFault:
                fatal = True
    assert fatal, "with the retry budget at 0 the transient fault must be fatal"

    result = {
        "numRequests": num_requests,
        "batchRows": batch_rows,
        "submitted": submitted,
        "rejected": rejected,
        "completed": len(results),
        # the SLO surface (ISSUE 12): per-stage latency percentiles from
        # the obs/hist.py histograms, via ServerHealth — queue-wait vs
        # batch-form vs dispatch vs readback, p50/p90/p99/p999 each
        "stageLatencyMs": health.stageLatencyMs,
        "admissionCapacity": server.admission,
        "inFlight": server.in_flight,
        "peakAdmissionDepth": int(peak_admit),
        "peakWindowDepth": int(peak_window),
        # the bounded-memory claim in bytes: the deepest the queues got,
        # priced at one staged batch each — versus the unbounded
        # alternative of `rejected` extra batches parked in memory
        "peakQueuedBytes": int((peak_admit + peak_window) * batch_nbytes),
        "shedCount": int(chan.stats.shed),
        "maxStalenessLag": int(chan.stats.max_lag),
        "stalenessCapacity": capacity,
        "retryCount": int(retries_paid),
        "retriesBitIdentical": True,  # asserted above
        "zeroDeadlock": True,  # asserted above (bounded join)
        "wallMs": (time.perf_counter() - t_start) * 1000.0,
    }
    log(
        f"overloadSoak: {rejected}/{num_requests} rejected at the door, queue "
        f"peaks {result['peakAdmissionDepth']}/{result['admissionCapacity']} admit "
        f"+ {result['peakWindowDepth']}/{result['inFlight']} window "
        f"({result['peakQueuedBytes'] / 1e6:.1f}MB), staleness lag "
        f"{result['maxStalenessLag']} < {capacity}, {retries_paid} transient "
        "retries bit-identical"
    )
    return result


def bench_hot_swap_soak(num_batches=96, batch_rows=512, d=32, num_swaps=24):
    """Robustness workload (ISSUE 10): versioned zero-pause model hot-swap
    under serving load, asserted in-process:

    1. **Zero-pause, zero-recompile swaps** — a trainer thread promotes
       `num_swaps` validated versions through `lifecycle.ModelLifecycle`
       while a MicroBatchServer drives the FUSED plan over `num_batches`
       batches. The jit compile counter must stay flat after warmup
       (model tensors are runtime operands, not baked constants), and
       per-batch p99 latency across the swap phase is reported against
       the no-swap steady state — the "zero pause" number.
    2. **Zero torn reads** — every served batch's modelVersion column
       must hold exactly ONE value, that value must have been promoted
       (never a rejected candidate), and versions must be monotone.
    3. **Gate + rollback** — a NaN-poisoned candidate is refused at the
       gate (`promoteRejected`); a bad-but-finite promotion followed by a
       guard-error window triggers the automatic rollback, which must
       restore the retained last-good version BIT-EXACTLY; the wall from
       first guard error to the first batch served on the rolled-back
       version is the rollback-to-recovery number.
    """
    import jax

    from flink_ml_tpu import flow
    from flink_ml_tpu.lifecycle import ModelLifecycle, PromotionRejected
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegressionModel,
    )
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.obs import tracing
    from flink_ml_tpu.pipeline import PipelineModel
    from flink_ml_tpu.serving import MicroBatchServer
    from flink_ml_tpu.table import Table
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(23)
    t_start = time.perf_counter()

    scaler = StandardScalerModel()
    scaler.mean = rng.standard_normal(d)
    scaler.std = np.abs(rng.standard_normal(d)) + 0.1
    scaler.set_input_col("features").set_output_col("features")
    model = OnlineLogisticRegressionModel()
    model.publish_model_arrays((np.zeros(d),), 0)
    model.set_features_col("features").set_prediction_col("pred")
    lifecycle = ModelLifecycle(model, retained=4, health_window=4, error_rate_trigger=0.5)
    pm = PipelineModel([scaler, model])
    server = MicroBatchServer(pm, in_flight=2, device_input=True, lifecycle=lifecycle)

    def batches(n):
        for _ in range(n):
            yield Table(
                {"features": rng.standard_normal((batch_rows, d), dtype=np.float32)}
            )

    def timed_serve(n):
        walls, versions = [], []
        t_prev = time.perf_counter()
        for out in server.serve(batches(n)):
            got = np.unique(np.asarray(out.column("modelVersion")))
            assert len(got) == 1, "torn read: one batch served by two versions"
            versions.append(int(got[0]))
            now = time.perf_counter()
            walls.append((now - t_prev) * 1000.0)
            t_prev = now
        return walls, versions

    # warmup + steady state (no swaps)
    timed_serve(4)
    tracing.install_jax_hooks()
    compiles_before = metrics.get_counter("jit.compiles", 0)
    steady_walls, _ = timed_serve(num_batches // 2)

    # swap phase: trainer promotes while the server serves
    accepted: list = []
    rejected_count = [0]
    base = np.zeros(d)

    def trainer():
        for i in range(1, num_swaps + 1):
            candidate = base + 0.01 * i
            if i % 6 == 0:  # NaN-poisoned update: the gate must refuse it
                poisoned = candidate.copy()
                poisoned[i % d] = np.nan
                try:
                    lifecycle.promote((poisoned,))
                except PromotionRejected:
                    rejected_count[0] += 1
                continue
            accepted.append(lifecycle.promote((candidate,)).version_id)
            time.sleep(0.001)

    t_swap = time.perf_counter()
    worker = flow.spawn(trainer, name="hotswap.trainer")
    swap_walls, served_versions = timed_serve(num_batches)
    worker.join(timeout=120.0)
    assert not worker.is_alive(), "trainer wedged"
    swap_phase_s = time.perf_counter() - t_swap

    compiles_during = metrics.get_counter("jit.compiles", 0) - compiles_before
    assert compiles_during == 0, f"{compiles_during} recompiles across {len(accepted)} swaps"
    valid = set(accepted) | {0}
    assert set(served_versions) <= valid, "a never-promoted version was served"
    assert served_versions == sorted(served_versions), "served versions went backwards"
    assert rejected_count[0] == num_swaps // 6, "every poisoned candidate must be refused"
    lifecycle.record_serve_ok()

    # rollback leg: bad-but-finite promotion slips the gate; guard errors
    # roll traffic back; recovery = first batch served on the good version
    good_version = model.model_version
    good_coeff = np.copy(model.coefficient)
    lifecycle.promote((base + 1e6,))
    t_trigger = time.perf_counter()
    for _ in range(4):
        lifecycle.record_guard_error(ValueError("downstream guard fired"))
    assert lifecycle.rollback_count == 1
    _, recovered = timed_serve(1)
    rollback_recovery_ms = (time.perf_counter() - t_trigger) * 1000.0
    assert recovered == [good_version], "post-rollback traffic must serve last-good"
    assert np.array_equal(model.coefficient, good_coeff), "rollback must be bit-exact"
    jax.block_until_ready([])

    p99 = lambda xs: float(np.percentile(np.asarray(xs), 99)) if xs else 0.0
    result = {
        "numBatches": num_batches,
        "batchRows": batch_rows,
        "swapCount": len(accepted),
        "promoteRejected": rejected_count[0],
        "rollbackCount": 1,
        "swapsPerSec": len(accepted) / swap_phase_s if swap_phase_s else 0.0,
        "steadyP50Ms": float(np.percentile(np.asarray(steady_walls), 50)),
        "steadyP99Ms": p99(steady_walls),
        "swapPhaseP50Ms": float(np.percentile(np.asarray(swap_walls), 50)),
        "swapPhaseP99Ms": p99(swap_walls),
        "rollbackRecoveryMs": rollback_recovery_ms,
        "recompilesDuringSwaps": int(compiles_during),  # asserted 0
        "tornReads": 0,  # asserted per batch above
        "servedVersionsMonotone": True,  # asserted above
        "rollbackBitExact": True,  # asserted above
        "wallMs": (time.perf_counter() - t_start) * 1000.0,
    }
    log(
        f"hotSwapSoak: {result['swapCount']} swaps at "
        f"{result['swapsPerSec']:.0f}/s under load, p99 {result['swapPhaseP99Ms']:.2f}ms "
        f"across swaps vs {result['steadyP99Ms']:.2f}ms steady, 0 recompiles, "
        f"{result['promoteRejected']} NaN candidates refused, rollback recovered "
        f"bit-exact in {rollback_recovery_ms:.1f}ms"
    )
    return result


def bench_serving_slo(
    d=24,
    rows_per_req=4,
    sweep=(250, 1000, 20000),
    phase_s=0.5,
    low_qps=40,
    low_n=30,
    deadline_ms=100.0,
    n_tenants=6,
    tenant_requests=240,
    tenant_d=512,
    in_budget=lambda: True,
):
    """The open-loop serving-SLO workload (ISSUE 19 / ROADMAP item 3),
    asserted in-process:

    1. **Bit-identity across batching modes** — the same request set
       served per-request, fixed-batch, and continuously-batched must
       produce bit-identical outputs per request (coalescing + padding
       only ever adds copies of real rows to row-wise kernels).
    2. **Continuous beats fixed where it should** — at low offered QPS
       continuous batching's p99 (flush on the forming budget) must beat
       fixed batching's (wait for a full bucket), and its goodput under a
       deadline must too; at saturation its goodput must be at least
       fixed's (both form full buckets there).
    3. **Open-loop saturation sweep** — arrivals follow a fixed schedule
       independent of completions (queueing delay stays honest, per the
       Spark perf-study methodology): offered QPS sweeps to saturation,
       reporting goodput (ok-within-deadline results/s), the saturation
       knee, per-stage p50/p99/p999, and the deadline-miss split.
    4. **Multi-tenant HBM paging, zero recompiles** — `n_tenants` models
       whose combined constants exceed `config.model_store_bytes` serve
       round-robin from ONE server through a `ModelStore`: the jit
       compile counter must stay flat across steady-state paging (model
       tensors are runtime operands), `hbm.live.model` must never exceed
       the budget, and the store's ledger parity must hold at the end.
    """
    import jax

    from flink_ml_tpu import config, flow
    from flink_ml_tpu.data.modelstore import ModelStore
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegressionModel,
    )
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.obs import memledger, tracing
    from flink_ml_tpu.pipeline import PipelineModel
    from flink_ml_tpu.serving import MicroBatchServer, ServerOverloaded
    from flink_ml_tpu.table import Table
    from flink_ml_tpu.utils import metrics

    rng = np.random.default_rng(19)
    t_start = time.perf_counter()
    tracing.install_jax_hooks()

    def scaler_pipeline():
        scaler = StandardScalerModel()
        scaler.mean = rng.standard_normal(d)
        scaler.std = np.abs(rng.standard_normal(d)) + 0.1
        scaler.set_input_col("features").set_output_col("scaled")
        norm = Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm")
        return PipelineModel([scaler, norm])

    pm = scaler_pipeline()
    feature = lambda rows: Table(
        {"features": rng.standard_normal((rows, d), dtype=np.float32)}
    )

    # warm every bucket shape the phases touch (compiles are a fixed cost
    # paid once per (plan, bucket); the SLO phases measure steady state)
    for rows in (8, 32):
        list(MicroBatchServer(pm, buckets=(8, 32)).serve(iter([feature(rows)])))

    # -- 1. bit-identity: request vs fixed vs continuous -------------------
    requests = [feature(int(r)) for r in rng.integers(1, 9, size=24)]

    def serve_all(server, batches):
        outputs = {}

        def collect():
            for r in server.results():
                outputs[r.seq] = r

        worker = flow.spawn(collect, name="slo.collect")
        seqs = [server.submit(b) for b in batches]
        server.close()
        worker.join(timeout=120.0)
        assert not worker.is_alive(), "collector wedged"
        return [outputs[s] for s in seqs]

    modes = {
        "request": MicroBatchServer(pm, buckets=(8, 32), batching="request", admission=64),
        "fixed": MicroBatchServer(
            pm, buckets=(8, 32), batching="fixed", form_rows=8, admission=64
        ),
        "continuous": MicroBatchServer(
            pm, buckets=(8, 32), batching="continuous", form_rows=32, admission=64
        ),
    }
    per_mode = {name: serve_all(s, requests) for name, s in modes.items()}
    for name in ("fixed", "continuous"):
        for ref, got, batch in zip(per_mode["request"], per_mode[name], requests):
            assert ref.status == got.status == "ok"
            assert got.table.num_rows == batch.num_rows
            assert np.array_equal(
                np.asarray(ref.table.column("norm")), np.asarray(got.table.column("norm"))
            ), f"{name} batching changed results vs the per-request path"

    # -- open-loop load phases ---------------------------------------------
    def run_phase(server, qps, duration_s, rows, tenant_fn=None, phase_deadline_ms=None):
        """Open-loop: arrivals at t0 + i/qps regardless of completions.
        Returns offered/goodput rates and client-observed latencies."""
        recv: dict = {}
        latencies: dict = {}
        sent: dict = {}

        def collect():
            for r in server.results():
                recv[r.seq] = r.status
                if r.seq in sent:
                    latencies[r.seq] = (time.monotonic() - sent[r.seq]) * 1000.0

        worker = flow.spawn(collect, name="slo.collect")
        payload = [feature(rows) for _ in range(8)]  # reuse: submit stays cheap
        interval = 1.0 / qps
        t0 = time.monotonic()
        i = offered = rejects = 0
        while True:
            target = t0 + i * interval
            if target > t0 + duration_s:
                break
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                now = time.monotonic()
                seq = server.submit(
                    payload[i % len(payload)],
                    deadline_ms=phase_deadline_ms,
                    tenant=None if tenant_fn is None else tenant_fn(i),
                )
                sent[seq] = now
                offered += 1
            except ServerOverloaded:
                rejects += 1
            i += 1
        server.close()
        worker.join(timeout=300.0)
        assert not worker.is_alive(), "collector wedged"
        elapsed = time.monotonic() - t0
        ok = sum(1 for s in recv.values() if s == "ok")
        late = sum(1 for s in recv.values() if s == "late")
        expired = sum(1 for s in recv.values() if s == "expired")
        lat = sorted(latencies.values())
        p = lambda q: lat[min(len(lat) - 1, int(q * len(lat)))] if lat else 0.0
        return {
            "offeredQps": i / elapsed,
            "goodputQps": ok / elapsed,
            "ok": ok,
            "late": late,
            "expired": expired,
            "rejected": rejects,
            "p50Ms": p(0.50),
            "p99Ms": p(0.99),
        }

    # -- 2. low offered QPS: the forming budget must bound latency ----------
    low = {}
    for name, kwargs in (
        ("fixed", dict(batching="fixed", form_rows=8)),
        ("continuous", dict(batching="continuous", form_rows=8)),
    ):
        server = MicroBatchServer(pm, buckets=(8,), admission=64, **kwargs)
        low[name] = run_phase(
            server, low_qps, low_n / low_qps, rows=1, phase_deadline_ms=deadline_ms
        )
    assert low["continuous"]["p99Ms"] < low["fixed"]["p99Ms"], (
        f"continuous p99 {low['continuous']['p99Ms']:.1f}ms must beat fixed "
        f"{low['fixed']['p99Ms']:.1f}ms at {low_qps} offered QPS"
    )
    assert low["continuous"]["goodputQps"] > low["fixed"]["goodputQps"], (
        "a full-bucket wait past the deadline must cost fixed batching goodput"
    )

    # -- 3. saturation sweep, both modes ------------------------------------
    sweeps = {"fixed": [], "continuous": []}
    health = None
    for qps in sweep:
        for name in ("fixed", "continuous"):
            if not in_budget():
                break
            server = MicroBatchServer(
                pm,
                buckets=(8, 32),
                batching=name,
                form_rows=32,
                admission=64,
                in_flight=2,
            )
            r = run_phase(server, qps, phase_s, rows=rows_per_req, phase_deadline_ms=deadline_ms)
            r["targetQps"] = qps
            sweeps[name].append(r)
            if name == "continuous":
                health = server.health()  # per-stage SLO surface
    cont_sweep, fixed_sweep = sweeps["continuous"], sweeps["fixed"]
    if cont_sweep and fixed_sweep:
        sat_cont = max(r["goodputQps"] for r in cont_sweep)
        sat_fixed = max(r["goodputQps"] for r in fixed_sweep)
        # 0.8 margin, not parity: both modes form full buckets at
        # saturation so the true ratio is ~1.0, but the 0.5s sweep phases
        # make the measured ratio noisy under scheduler jitter (observed
        # spread on a busy host reaches ~0.9) — the assert guards the
        # collapse mode (per-request flushing ~0.6x), not the noise floor
        assert sat_cont >= 0.8 * sat_fixed, (
            f"continuous saturated goodput {sat_cont:.0f}/s fell below fixed "
            f"{sat_fixed:.0f}/s — coalescing must not cost capacity"
        )
    else:  # sweep cut short by the budget: report the low-QPS phase rates
        sat_cont = low["continuous"]["goodputQps"]
        sat_fixed = low["fixed"]["goodputQps"]
    # the knee: the highest offered rate the server still served ~fully
    knee = 0.0
    for r in cont_sweep:
        if r["goodputQps"] >= 0.85 * r["offeredQps"]:
            knee = max(knee, r["offeredQps"])

    # -- 4. multi-tenant paging: N models, budget for ~3, zero recompiles ---
    tenants = [f"tenant{i}" for i in range(n_tenants)]
    probe_store = ModelStore(budget_bytes=None)

    def tenant_model(seed):
        trng = np.random.default_rng(seed)
        scaler = StandardScalerModel()
        scaler.mean = trng.standard_normal(tenant_d)
        scaler.std = np.abs(trng.standard_normal(tenant_d)) + 0.1
        scaler.set_input_col("features").set_output_col("features")
        olr = OnlineLogisticRegressionModel()
        olr.publish_model_arrays((trng.standard_normal(tenant_d),), 0)
        olr.set_features_col("features").set_prediction_col("pred")
        return PipelineModel([scaler, olr])

    tenant_models = {t: tenant_model(100 + i) for i, t in enumerate(tenants)}
    probe_store.register(tenants[0], tenant_models[tenants[0]])
    per_model = probe_store.estimated_nbytes(tenants[0])
    budget = int(per_model * 3.3)  # room for 3 of n_tenants residents
    assert n_tenants * per_model > budget, "the paging phase must exceed the budget"
    store = ModelStore(budget_bytes=budget)
    for t in tenants:
        store.register(t, tenant_models[t], quota=16)
    server = MicroBatchServer(
        store=store,
        buckets=(8, 32),
        batching="continuous",
        form_rows=32,
        admission=64,
    )
    tfeature = lambda rows: Table(
        {"features": rng.standard_normal((rows, tenant_d), dtype=np.float32)}
    )

    def serve_tenants(count, start=0):
        """Round-robin tenant requests; every submit samples the model
        ledger so the budget claim covers the whole phase, not endpoints."""
        outputs = {}
        peak = 0

        def collect():
            for r in server.results():
                outputs[r.seq] = r

        worker = flow.spawn(collect, name="slo.tenants")
        for i in range(count):
            while True:  # closed-loop pacing: this phase measures paging
                try:
                    server.submit(
                        tfeature(rows_per_req), tenant=tenants[(start + i) % n_tenants]
                    )
                    break
                except ServerOverloaded:
                    time.sleep(0.002)
            peak = max(peak, memledger.live_bytes("model"))
        server.close()
        worker.join(timeout=300.0)
        assert not worker.is_alive(), "tenant collector wedged"
        peak = max(peak, memledger.live_bytes("model"))
        return outputs, peak

    # warmup: every tenant's fused plan compiles ONCE per bucket shape
    # (first touch, through the paging store); the steady phase below then
    # pages with the compile counter pinned
    for t in tenants:
        list(
            MicroBatchServer(store.acquire(t), buckets=(8, 32)).serve(
                iter([tfeature(8), tfeature(32)])
            )
        )
    outputs, _ = serve_tenants(n_tenants * 2)
    assert all(r.status == "ok" for r in outputs.values())
    server = MicroBatchServer(
        store=store, buckets=(8, 32), batching="continuous", form_rows=32, admission=64
    )
    compiles_before = metrics.get_counter("jit.compiles", 0)
    page_ins_before = metrics.get_counter("modelstore.pageIn", 0)
    t_paged = time.perf_counter()
    outputs, peak_model_bytes = serve_tenants(tenant_requests, start=1)
    paged_s = time.perf_counter() - t_paged
    recompiles = metrics.get_counter("jit.compiles", 0) - compiles_before
    page_ins = metrics.get_counter("modelstore.pageIn", 0) - page_ins_before
    assert recompiles == 0, f"{recompiles} recompiles during steady-state paging"
    assert len(outputs) == tenant_requests and all(
        r.status == "ok" for r in outputs.values()
    ), "every tenant request must retire ok"
    assert peak_model_bytes <= budget, (
        f"hbm.live.model peaked at {peak_model_bytes} over the {budget} budget"
    )
    assert page_ins > 0, "the round-robin phase must actually page"
    store.check_ledger_parity()
    jax.block_until_ready([])

    offered_top = max((r["offeredQps"] for r in cont_sweep), default=float(low_qps))
    metrics.set_gauge("serving.offeredQps", offered_top)
    metrics.set_gauge("serving.goodputQps", sat_cont)
    metrics.set_gauge("serving.saturationQps", knee)

    result = {
        "offeredQps": offered_top,
        "goodputQps": sat_cont,
        "saturationQps": knee,
        "fixedGoodputQps": sat_fixed,
        "lowQps": {
            "offered": low_qps,
            "continuousP99Ms": low["continuous"]["p99Ms"],
            "fixedP99Ms": low["fixed"]["p99Ms"],
            "continuousGoodputQps": low["continuous"]["goodputQps"],
            "fixedGoodputQps": low["fixed"]["goodputQps"],
        },
        "sweep": {name: rs for name, rs in sweeps.items()},
        "deadlineMissLate": sum(r["late"] for r in cont_sweep),
        "deadlineMissExpired": sum(r["expired"] for r in cont_sweep),
        "rejected": sum(r["rejected"] for r in cont_sweep),
        "stageLatencyMs": health.stageLatencyMs if health else None,
        # the multi-tenant paging phase
        "tenants": n_tenants,
        "modelStoreBudgetBytes": budget,
        "perModelBytes": int(per_model),
        "pageInCount": int(page_ins),
        "pageInQps": page_ins / paged_s if paged_s else 0.0,
        "peakModelBytes": int(peak_model_bytes),
        "modelStore": store.stats,
        "recompileCount": int(recompiles),  # asserted 0
        "bitIdentical": True,  # asserted above
        "peakHbmBytes": int(memledger.peak_bytes()),
        "wallMs": (time.perf_counter() - t_start) * 1000.0,
    }
    log(
        f"servingSlo: knee {knee:.0f} req/s of {offered_top:.0f} offered, goodput "
        f"{sat_cont:.0f}/s continuous vs {sat_fixed:.0f}/s fixed; low-QPS p99 "
        f"{low['continuous']['p99Ms']:.1f}ms vs {low['fixed']['p99Ms']:.1f}ms; "
        f"{n_tenants} tenants in a {budget / 1e3:.0f}KB budget paged {page_ins}x "
        f"({result['pageInQps']:.0f}/s) with 0 recompiles, peak model bytes "
        f"{peak_model_bytes}"
    )
    return result


def bench_multichip_collectives(device_counts=(2, 8), in_budget=lambda: True):
    """The comm-layer workload (ISSUE 4): per-device-count collective
    traffic and wall time from scripts/bench_collectives.py — bucketed
    all-reduce (chunk count + chunked vs monolithic wall), the SparCML
    index-value gradient reduce at the sparseWideLR shape (sparse wire
    bytes vs dense-equivalent — the traffic-proportionality number), and
    a dense SGD fit with the overlap schedule off vs on (bit-identity
    asserted in-process). Each device count needs its own jax backend
    (xla_force_host_platform_device_count must win before jax initializes),
    hence one subprocess per N — the dryrun_multichip substrate promoted
    to a first-class BENCH entry. Skips gracefully when no multi-device
    run fits the budget (the entry reports why instead of nulling out)."""
    import subprocess

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts", "bench_collectives.py"
    )
    runs = {}
    for n in device_counts:
        if n < 2:
            continue  # collectives need a second participant
        if not in_budget():
            runs[str(n)] = {"skipped": "budget"}
            continue
        try:
            proc = subprocess.run(
                [sys.executable, script, "--devices", str(n)],
                capture_output=True,
                text=True,
                timeout=240,
            )
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr.strip().splitlines()[-1:] or "nonzero exit")
            runs[str(n)] = json.loads(proc.stdout.strip().splitlines()[-1])
            r = runs[str(n)]
            log(
                f"multichipCollectives[{n}]: {r['denseAllReduce']['chunkCount']} buckets, "
                f"chunked {r['denseAllReduce']['chunkedMs']:.2f}ms vs mono "
                f"{r['denseAllReduce']['monolithicMs']:.2f}ms; sparse ratio "
                f"{r['sparseGradReduce']['sparseRatio']:.4f}; overlap SGD "
                f"{r['overlapSgd']['overlapMs']:.0f}ms vs eager {r['overlapSgd']['eagerMs']:.0f}ms"
            )
        except Exception as e:
            log(f"multichipCollectives[{n}] failed: {e!r}")
            runs[str(n)] = {"skipped": repr(e)}
    if not any("skipped" not in r for r in runs.values()):
        return {"skipped": "no multi-device run completed", "runs": runs}
    return {"substrate": "virtual_cpu_devices", "runs": runs}


def bench_aot_cold_start(in_budget=lambda: True):
    """The AOT-program-bank cold-start entry (ISSUE 20 / ROADMAP item 5,
    docs/performance.md §12): fresh-process first-serve walls with the
    bank on vs off, plus the no-compile SLA asserted both cross-process
    and in-process.

    Three subprocesses run scripts/coldstart_smoke.py against one bank
    directory: ``populate`` (warmup AOT-compiles + back-fills the bank),
    ``serve`` (fresh process warm-loads the bank and serves its first
    request — the script itself exits 1 unless that dispatch performed
    zero kernel traces AND zero XLA backend compiles), and ``baseline``
    (bank off: the same first serve pays trace + compile). Asserted
    here: serveTraceCount == serveCompileCount == 0 on the banked serve,
    and the output sha256 of the bank-loaded executable matches the
    freshly-compiled baseline bit-for-bit. Then the same workload runs
    IN this process — once bank-off (fresh compile) and once under
    ``config.program_bank_mode`` (warm-load + hit) — and the two output
    buffers must compare equal byte-for-byte with a zero trace delta on
    the banked run."""
    import shutil
    import subprocess
    import tempfile

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts", "coldstart_smoke.py"
    )
    bank_dir = tempfile.mkdtemp(prefix="aot-bank.")

    def run(mode):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, script, bank_dir, mode],
            capture_output=True,
            text=True,
            timeout=240,
        )
        wall_ms = (time.perf_counter() - t0) * 1000.0
        if proc.returncode != 0:
            tail = "; ".join(proc.stderr.strip().splitlines()[-3:])
            raise RuntimeError(f"coldstart_smoke {mode}: exit {proc.returncode}: {tail}")
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        out["processWallMs"] = wall_ms
        return out

    try:
        populate = run("populate")
        if not in_budget():
            return {"skipped": "budget", "populate": populate}
        serve = run("serve")
        baseline = run("baseline")

        assert serve["serveTraceCount"] == 0.0 and serve["serveCompileCount"] == 0.0, (
            f"no-compile SLA violated on fresh-process serve: {serve}"
        )
        assert serve["bankHits"] >= 1.0 and serve["bankLoads"] >= 1.0, (
            f"banked serve never hit the bank: {serve}"
        )
        assert serve["outSha"] == baseline["outSha"], (
            "bank-loaded executable output diverged from freshly-compiled "
            f"baseline: {serve['outSha']} != {baseline['outSha']}"
        )

        # in-process bit-identity + zero-trace check: same workload, fresh
        # compile vs warm-loaded bank hit, byte-compared
        import importlib.util

        spec = importlib.util.spec_from_file_location("coldstart_smoke", script)
        smoke = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(smoke)

        from flink_ml_tpu import config
        from flink_ml_tpu.serving import MicroBatchServer
        from flink_ml_tpu.utils import metrics

        def serve_once():
            model, example = smoke.build_workload()
            server = MicroBatchServer(model, buckets=smoke.BUCKETS)
            out = list(server.serve(iter([example])))[0]
            return np.ascontiguousarray(
                np.asarray(out.column("norm"), dtype=np.float32)
            )

        fresh = serve_once()
        with config.program_bank_mode(bank_dir):
            before = metrics.snapshot()
            banked = serve_once()
            delta = metrics.snapshot_delta(before, metrics.snapshot())["counters"]
        assert delta.get("jit.traces", 0) == 0, (
            f"in-process banked serve traced: {delta}"
        )
        assert fresh.tobytes() == banked.tobytes(), (
            "in-process bank-loaded output is not bit-identical to the "
            "freshly-compiled one"
        )

        log(
            f"aotColdStart: cold start {serve['coldStartMs']:.0f}ms banked vs "
            f"{baseline['coldStartMs']:.0f}ms baseline; first serve "
            f"{serve['firstServeMs']:.1f}ms vs {baseline['firstServeMs']:.1f}ms; "
            f"bank load {serve['bankLoadMs']:.1f}ms ({serve['bankLoads']:.0f} "
            "programs); zero traces/compiles + bit-identity verified"
        )
        return {
            "coldStartMs": serve["coldStartMs"],
            "baselineColdStartMs": baseline["coldStartMs"],
            "firstServeMs": serve["firstServeMs"],
            "baselineFirstServeMs": baseline["firstServeMs"],
            "populateMs": populate["warmupMs"],
            "bankLoadMs": serve["bankLoadMs"],
            "bankLoads": serve["bankLoads"],
            "bankHits": serve["bankHits"],
            "bankMisses": serve["bankMisses"],
            "serveTraceCount": serve["serveTraceCount"],
            "serveCompileCount": serve["serveCompileCount"],
            "baselineServeTraceCount": baseline["serveTraceCount"],
            "baselineServeCompileCount": baseline["serveCompileCount"],
            "bitIdentical": True,
        }
    finally:
        shutil.rmtree(bank_dir, ignore_errors=True)


def main(argv):
    _enable_compilation_cache()
    budget = float(os.environ.get("BENCH_BUDGET_S", "420"))
    deadline = time.monotonic() + budget
    logreg_rows = 10_000_000
    if "--logreg-rows" in argv:
        try:
            logreg_rows = int(argv[argv.index("--logreg-rows") + 1])
        except (IndexError, ValueError):
            log("--logreg-rows needs an integer; using default")

    details = {
        "logisticregression": None,
        "logisticregressionTrace": None,
        "logisticregressionAmortized": None,
        "lossParity": None,
        "cpuBaseline": None,
        "sparseWideLR": None,
        "kmeans": None,
        "pipelineServing": None,
        "inputPipeline": None,
        "wholeFitDispatch": None,
        "fleetSweep": None,
        "checkpointResume": None,
        "multiHostCheckpoint": None,
        "elasticRecovery": None,
        "overloadSoak": None,
        "hotSwapSoak": None,
        "servingSlo": None,
        "aotColdStart": None,
        "multichipCollectives": None,
    }
    value, vs_baseline, vs_baseline_source = None, None, None

    def in_budget(reserve=30.0):
        return time.monotonic() < deadline - reserve

    try:
        try:
            details["logisticregression"] = bench_logreg(logreg_rows, in_budget)
            value = details["logisticregression"]["throughputPerChip"]
        except Exception as e:
            log(f"logisticregression stage failed: {e!r}")

        if in_budget():
            try:  # reuses the executables the warm runs just compiled
                details["logisticregressionTrace"] = bench_logreg_trace(logreg_rows)
                if details["logisticregression"] is not None and isinstance(
                    details["logisticregressionTrace"].get("trainLoopMFU_trace"), float
                ):
                    details["logisticregression"]["trainLoopMFU"] = details[
                        "logisticregressionTrace"
                    ]["trainLoopMFU_trace"]
                    details["logisticregression"]["trainLoopMFUSource"] = "profiler_trace"
            except Exception as e:
                log(f"logisticregression trace stage failed: {e!r}")

        if in_budget(reserve=60.0):
            try:
                details["logisticregressionAmortized"] = bench_logreg_amortized(
                    logreg_rows, in_budget=in_budget
                )
            except Exception as e:
                log(f"logisticregression amortized stage failed: {e!r}")

        if "--skip-parity" not in argv and in_budget():
            try:
                details["lossParity"] = bench_loss_parity()
            except Exception as e:
                log(f"loss parity stage failed: {e!r}")

        if "--skip-cpu" not in argv and in_budget(reserve=150.0):
            # reserve covers the baseline's worst observed cost (~65s) with
            # slack for slower hosts, so the finally-printed JSON beats any
            # external harness timeout
            try:
                details["cpuBaseline"] = bench_cpu_baseline(logreg_rows)
                if details["logisticregression"] is not None:
                    # job-level ratio: total TPU throughput vs the whole-host
                    # CPU run of the same job (NOT per-chip vs host)
                    vs_baseline = (
                        details["logisticregression"]["inputThroughput"]
                        / details["cpuBaseline"]["inputThroughput"]
                    )
                    vs_baseline_source = "numpy_cpu_same_job_total_throughput"
            except Exception as e:
                log(f"cpu baseline stage failed: {e!r}")

        if in_budget():
            try:
                details["sparseWideLR"] = bench_wide_sparse_lr()
            except Exception as e:
                log(f"sparseWideLR stage failed: {e!r}")

        if in_budget():
            try:
                details["sparse2dMesh"] = bench_sparse_2d_mesh()
            except Exception as e:
                log(f"sparse2dMesh stage failed: {e!r}")

        if in_budget():
            try:
                details["kmeans"] = bench_kmeans()
            except Exception as e:
                log(f"kmeans stage failed: {e!r}")

        if in_budget():
            try:
                details["pipelineServing"] = bench_pipeline_serving()
            except Exception as e:
                log(f"pipelineServing stage failed: {e!r}")

        if in_budget():
            try:
                details["inputPipeline"] = bench_input_pipeline()
            except Exception as e:
                log(f"inputPipeline stage failed: {e!r}")

        if in_budget():
            try:
                details["wholeFitDispatch"] = bench_whole_fit_dispatch()
            except Exception as e:
                log(f"wholeFitDispatch stage failed: {e!r}")

        if in_budget():
            try:
                details["fleetSweep"] = bench_fleet_sweep(in_budget=in_budget)
            except Exception as e:
                log(f"fleetSweep stage failed: {e!r}")

        if in_budget():
            try:
                details["checkpointResume"] = bench_checkpoint_resume()
            except Exception as e:
                log(f"checkpointResume stage failed: {e!r}")

        if in_budget():
            try:
                details["multiHostCheckpoint"] = bench_multihost_checkpoint()
            except Exception as e:
                log(f"multiHostCheckpoint stage failed: {e!r}")

        if in_budget():
            try:
                details["elasticRecovery"] = bench_elastic_recovery()
            except Exception as e:
                log(f"elasticRecovery stage failed: {e!r}")

        if in_budget():
            try:
                details["overloadSoak"] = bench_overload_soak()
            except Exception as e:
                log(f"overloadSoak stage failed: {e!r}")

        if in_budget():
            try:
                details["hotSwapSoak"] = bench_hot_swap_soak()
            except Exception as e:
                log(f"hotSwapSoak stage failed: {e!r}")

        if in_budget():
            try:
                details["servingSlo"] = bench_serving_slo(in_budget=in_budget)
            except Exception as e:
                log(f"servingSlo stage failed: {e!r}")

        if in_budget():
            try:
                details["aotColdStart"] = bench_aot_cold_start(in_budget=in_budget)
            except Exception as e:
                log(f"aotColdStart stage failed: {e!r}")

        if in_budget():
            try:
                details["multichipCollectives"] = bench_multichip_collectives(
                    in_budget=in_budget
                )
            except Exception as e:
                log(f"multichipCollectives stage failed: {e!r}")

        try:  # recorded separately by scripts/bench_sweep.py; attach summary
            sweep_path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "benchmarks", "SWEEP.json"
            )
            if os.path.exists(sweep_path):
                with open(sweep_path) as f:
                    sweep = json.load(f)
                details["sweep"] = {"file": "benchmarks/SWEEP.json", "meta": sweep["meta"]}
        except Exception as e:
            log(f"sweep summary attach failed: {e!r}")
    finally:
        print(
            json.dumps(
                {
                    "metric": "logisticregression_train_throughput",
                    "value": round(value, 2) if value is not None else None,
                    "unit": "records/s/chip",
                    "vs_baseline": round(vs_baseline, 2) if vs_baseline is not None else None,
                    "vs_baseline_source": vs_baseline_source,
                    "details": details,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main(sys.argv[1:])
