// Spillable columnar data cache — the native runtime component of the
// framework's IO layer.
//
// TPU-native re-design of the reference's datacache subsystem
// (flink-ml-iteration/.../datacache/nonkeyed/: DataCacheWriter.java:37-153,
// MemorySegmentWriter.java, FileSegmentWriter.java, DataCacheReader.java,
// Segment.java, ListStateWithCache.java): append-only segments live in
// memory until a budget is exhausted, then spill to an append-only file;
// reads are position-addressed and zero-copy into caller buffers. Exposed
// through a C ABI consumed via ctypes (flink_ml_tpu/native/__init__.py).
//
// Build: g++ -O2 -shared -fPIC -o libdatacache.so datacache.cc

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Segment {
  // exactly one of: memory-resident bytes, or a [offset, size) span of the
  // cache's spill file (Segment.java holds MemorySegments or a spilled path)
  std::vector<uint8_t> bytes;
  bool spilled = false;
  uint64_t file_offset = 0;
  uint64_t size = 0;
};

struct DataCache {
  std::mutex mu;
  std::vector<Segment> segments;
  uint64_t memory_budget;
  uint64_t memory_used = 0;
  uint64_t spilled_bytes = 0;
  long spilled_segments = 0;
  std::string spill_path;
  FILE* spill_file = nullptr;  // lazily created append-only spill store
};

bool ensure_spill_file(DataCache* dc) {
  if (dc->spill_file != nullptr) return true;
  dc->spill_file = std::fopen(dc->spill_path.c_str(), "w+b");
  return dc->spill_file != nullptr;
}

}  // namespace

extern "C" {

void* dc_create(uint64_t memory_budget_bytes, const char* spill_path) {
  auto* dc = new DataCache();
  dc->memory_budget = memory_budget_bytes;
  dc->spill_path = spill_path ? spill_path : "";
  return dc;
}

void dc_destroy(void* handle) {
  auto* dc = static_cast<DataCache*>(handle);
  if (dc->spill_file != nullptr) {
    std::fclose(dc->spill_file);
    std::remove(dc->spill_path.c_str());
  }
  delete dc;
}

// Appends one segment; returns its id, or -1 on failure.
long dc_append(void* handle, const void* data, uint64_t nbytes) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  Segment seg;
  seg.size = nbytes;
  if (dc->memory_used + nbytes <= dc->memory_budget || dc->spill_path.empty()) {
    // memory-resident (MemorySegmentWriter path)
    seg.bytes.assign(static_cast<const uint8_t*>(data),
                     static_cast<const uint8_t*>(data) + nbytes);
    dc->memory_used += nbytes;
  } else {
    // spill (FileSegmentWriter path)
    if (!ensure_spill_file(dc)) return -1;
    if (std::fseek(dc->spill_file, 0, SEEK_END) != 0) return -1;
    long pos = std::ftell(dc->spill_file);
    if (pos < 0) return -1;
    if (std::fwrite(data, 1, nbytes, dc->spill_file) != nbytes) return -1;
    std::fflush(dc->spill_file);
    seg.spilled = true;
    seg.file_offset = static_cast<uint64_t>(pos);
    dc->spilled_bytes += nbytes;
    dc->spilled_segments += 1;
  }
  dc->segments.push_back(std::move(seg));
  return static_cast<long>(dc->segments.size()) - 1;
}

long dc_num_segments(void* handle) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  return static_cast<long>(dc->segments.size());
}

// Size in bytes of segment `seg`, or 0 if out of range.
uint64_t dc_segment_size(void* handle, long seg) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  if (seg < 0 || seg >= static_cast<long>(dc->segments.size())) return 0;
  return dc->segments[seg].size;
}

// Copies segment `seg` into `out` (caller allocates dc_segment_size bytes).
// Returns 0 on success.
int dc_read(void* handle, long seg, void* out) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  if (seg < 0 || seg >= static_cast<long>(dc->segments.size())) return 1;
  const Segment& s = dc->segments[seg];
  if (!s.spilled) {
    std::memcpy(out, s.bytes.data(), s.size);
    return 0;
  }
  if (std::fseek(dc->spill_file, static_cast<long>(s.file_offset), SEEK_SET) != 0)
    return 2;
  if (std::fread(out, 1, s.size, dc->spill_file) != s.size) return 3;
  return 0;
}

uint64_t dc_memory_used(void* handle) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  return dc->memory_used;
}

long dc_spilled_segments(void* handle) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  return dc->spilled_segments;
}

uint64_t dc_spilled_bytes(void* handle) {
  auto* dc = static_cast<DataCache*>(handle);
  std::lock_guard<std::mutex> lock(dc->mu);
  return dc->spilled_bytes;
}

// Fast float64 CSV/whitespace parser: fills `out` with up to max_out values
// parsed from text[0..len); returns the number parsed. Commas, semicolons,
// whitespace and newlines all delimit.
long dc_parse_csv_doubles(const char* text, uint64_t len, double* out,
                          uint64_t max_out) {
  uint64_t count = 0;
  const char* p = text;
  const char* end = text + len;
  while (p < end && count < max_out) {
    while (p < end && (*p == ',' || *p == ';' || *p == ' ' || *p == '\t' ||
                       *p == '\n' || *p == '\r'))
      ++p;
    if (p >= end) break;
    char* next = nullptr;
    double value = std::strtod(p, &next);
    if (next == p) {  // unparsable token: skip it
      while (p < end && !(*p == ',' || *p == ';' || *p == ' ' || *p == '\t' ||
                          *p == '\n' || *p == '\r'))
        ++p;
      continue;
    }
    out[count++] = value;
    p = next;
  }
  return static_cast<long>(count);
}

}  // extern "C"
