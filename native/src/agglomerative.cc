// Native agglomerative-clustering merge loop.
//
// Mirrors the numpy nearest-neighbour-cache algorithm in
// flink_ml_tpu/models/clustering/agglomerativeclustering.py::_cluster_block
// operation for operation (same Lance-Williams arithmetic in double, same
// first-minimum tie-breaking, same cache maintenance), so the merge log is
// bit-identical to the Python fallback and the committed goldens — only
// faster: the Python loop costs ~0.3 ms per merge on this single-core
// host, this loop runs the whole 990-merge benchmark block in ~2 ms.
// (Reference semantics: clustering/agglomerativeclustering/
// AgglomerativeClustering.java nearest-neighbour agglomeration.)

#include <cmath>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

constexpr double kInf = std::numeric_limits<double>::infinity();

enum Linkage { kSingle = 0, kComplete = 1, kAverage = 2, kWard = 3 };

inline double lance_williams(double d_ik, double d_jk, double d_ij,
                             double size_i, double size_j, double size_k,
                             int linkage) {
  switch (linkage) {
    case kSingle:
      return d_ik < d_jk ? d_ik : d_jk;
    case kComplete:
      return d_ik > d_jk ? d_ik : d_jk;
    case kAverage:
      return (size_i * d_ik + size_j * d_jk) / (size_i + size_j);
    default: {  // ward (euclidean)
      // grouping matches numpy's `(s_i + s_k) * d_ik**2` evaluation
      // (square first) so results are bit-identical to the Python loop
      double total = size_i + size_j + size_k;
      return std::sqrt(((size_i + size_k) * (d_ik * d_ik) +
                        (size_j + size_k) * (d_jk * d_jk) -
                        size_k * (d_ij * d_ij)) /
                       total);
    }
  }
}

}  // namespace

extern "C" {

// Runs the merge loop over a dense distance matrix (row-major n*n, the
// caller already set the diagonal to +inf; the matrix is consumed in
// place). Writes up to n-1 merge rows (id1, id2, distance, mergedSize)
// into merges_out and per-row labels at the stop point (min original row
// index of each cluster; caller compacts) into pred_out.
// Returns the number of merges logged.
long agg_cluster(double* dist, long n, int linkage, double threshold,
                 int has_threshold, long num_clusters, int compute_full_tree,
                 double* merges_out, int32_t* pred_out) {
  if (n <= 0) {
    return 0;
  }
  std::vector<double> sizes(n, 1.0);
  std::vector<long> cluster_ids(n);
  std::vector<double> row_min(n, kInf);
  std::vector<long> row_arg(n, 0);
  std::vector<char> alive(n, 1);
  for (long i = 0; i < n; ++i) cluster_ids[i] = i;
  if (n > 1) {
    for (long i = 0; i < n; ++i) {
      const double* row = dist + i * n;
      double m = row[0];
      long a = 0;
      for (long j = 1; j < n; ++j)
        if (row[j] < m) { m = row[j]; a = j; }
      row_min[i] = m;
      row_arg[i] = a;
    }
  }
  // union-find over merge order; root keeps the min original row index
  std::vector<long> parent(n);
  std::vector<long> min_row(n);
  for (long i = 0; i < n; ++i) { parent[i] = i; min_row[i] = i; }
  auto find = [&](long x) {
    while (parent[x] != x) { parent[x] = parent[parent[x]]; x = parent[x]; }
    return x;
  };

  long num_active = n;
  long num_merges = 0;
  long stop_at = -1;
  while (num_active > 1) {
    // global closest pair: first minimum of the cached row minima
    long i = 0;
    double best = row_min[0];
    for (long r = 1; r < n; ++r)
      if (row_min[r] < best) { best = row_min[r]; i = r; }
    long j = row_arg[i];
    double d_ij = best;
    bool stop_hit = has_threshold ? (d_ij > threshold)
                                  : (num_active <= num_clusters);
    if (stop_hit && stop_at < 0) {
      // labels are the state BEFORE this iteration's merge: merges from
      // here on belong to the full tree only (python: merge_members[:stop_at])
      stop_at = num_merges;
      for (long r = 0; r < n; ++r) pred_out[r] = (int32_t)min_row[find(r)];
      if (!compute_full_tree) break;
    }
    long id_i = cluster_ids[i], id_j = cluster_ids[j];
    double lo = (double)(id_i < id_j ? id_i : id_j);
    double hi = (double)(id_i < id_j ? id_j : id_i);
    merges_out[num_merges * 4 + 0] = lo;
    merges_out[num_merges * 4 + 1] = hi;
    merges_out[num_merges * 4 + 2] = d_ij;
    merges_out[num_merges * 4 + 3] = sizes[i] + sizes[j];

    double* row_i = dist + i * n;
    double* row_j = dist + j * n;
    double size_i = sizes[i], size_j = sizes[j];
    // Lance-Williams row update against every live cluster k, plus the
    // same nearest-neighbour cache maintenance as the numpy version
    for (long k = 0; k < n; ++k) {
      if (!alive[k] || k == i || k == j) continue;
      double d_ik = row_i[k], d_jk = row_j[k];
      double nr = lance_williams(d_ik, d_jk, d_ij, size_i, size_j, sizes[k],
                                 linkage);
      row_i[k] = nr;
      dist[k * n + i] = nr;
      if (nr < row_min[k]) {
        row_min[k] = nr;
        row_arg[k] = i;
      } else if (row_arg[k] == i || row_arg[k] == j) {
        row_arg[k] = -1;  // stale: rescan below
      }
    }
    row_i[i] = kInf;
    row_i[j] = kInf;
    for (long k = 0; k < n; ++k) {
      dist[j * n + k] = kInf;
      dist[k * n + j] = kInf;
    }
    alive[j] = 0;
    row_min[j] = kInf;
    row_arg[j] = j;
    // i recomputes its nearest
    {
      double m = kInf;
      long a = 0;
      for (long k = 0; k < n; ++k)
        if (row_i[k] < m) { m = row_i[k]; a = k; }
      row_min[i] = m;
      row_arg[i] = a;
    }
    for (long k = 0; k < n; ++k) {
      if (row_arg[k] == -1) {
        const double* row = dist + k * n;
        double m = kInf;
        long a = 0;
        for (long c = 0; c < n; ++c)
          if (row[c] < m) { m = row[c]; a = c; }
        row_min[k] = m;
        row_arg[k] = a;
      }
    }
    sizes[i] += size_j;
    cluster_ids[i] = n + num_merges;
    // label bookkeeping up to the stop point happens after the loop via
    // union-find replay; record unions as we go
    long ri = find(i), rj = find(j);
    if (ri != rj) {
      parent[rj] = ri;
      if (min_row[rj] < min_row[ri]) min_row[ri] = min_row[rj];
    }
    ++num_merges;
    --num_active;
  }
  if (stop_at < 0) {  // never hit a stop criterion: labels at loop end
    for (long r = 0; r < n; ++r) pred_out[r] = (int32_t)min_row[find(r)];
  }
  return num_merges;
}

}  // extern "C"
