// Native hashing-trick kernels — single-pass render+hash+bucket for the
// FeatureHasher/HashingTF hot path.
//
// The reference hashes categorical cells as guava murmur3_32(0) over the
// UTF-16 code units of "col=" + String.valueOf(cell)
// (flink-ml-lib/.../feature/featurehasher/FeatureHasher.java:60-118), then
// buckets with Math.abs + mod. On a single-core host the Python/numpy
// pipeline (render 30M doubles to strings, concat, vectorized murmur)
// costs minutes at benchmark scale; this C path renders each double with
// Java Double.toString semantics (shortest round-trip digits via
// std::to_chars, Java's decimal/scientific form switch at 1e-3/1e7) and
// hashes it in one pass without materializing Python strings.
//
// Build: compiled together with datacache.cc into the runtime .so
// (flink_ml_tpu/native/__init__.py).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#if defined(__cpp_lib_to_chars)
#include <charconv>
#endif

namespace {

constexpr uint32_t kC1 = 0xcc9e2d51u;
constexpr uint32_t kC2 = 0x1b873593u;

inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

inline uint32_t mix_k1(uint32_t k1) {
  k1 *= kC1;
  k1 = rotl32(k1, 15);
  return k1 * kC2;
}

inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5 + 0xe6546b64u;
}

inline uint32_t fmix(uint32_t h1, uint32_t length) {
  h1 ^= length;
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

// guava Murmur3_32.hashUnencodedChars over UTF-16 code units.
inline int32_t murmur3_units(const uint16_t* units, long len) {
  uint32_t h1 = 0;
  long i = 0;
  for (; i + 1 < len; i += 2) {
    uint32_t k1 = (uint32_t)units[i] | ((uint32_t)units[i + 1] << 16);
    h1 = mix_h1(h1, mix_k1(k1));
  }
  if (i < len) h1 ^= mix_k1((uint32_t)units[i]);
  return (int32_t)fmix(h1, (uint32_t)(2 * len));
}

// FeatureHasher.updateMap bucketing: Math.abs (keeping Integer.MIN_VALUE)
// then a non-negative mod.
inline int32_t bucket(int32_t h, int32_t num_features) {
  if (h != INT32_MIN && h < 0) h = -h;
  int32_t m = h % num_features;
  return m < 0 ? m + num_features : m;
}

// Java Double.toString(v) rendered as UTF-16 units appended at `out`;
// returns the number of units written. Digits are the shortest round-trip
// sequence (std::to_chars scientific), placed decimal-style for
// 1e-3 <= |v| < 1e7 and as d.dddE±x otherwise — the Double.toString
// contract. (Same JDK<19 shortest-digit caveat as
// models/feature/stringindexer.py:_java_double_to_string.)
inline long render_java_double(double v, uint16_t* out) {
  long n = 0;
  if (std::isnan(v)) {
    for (const char* p = "NaN"; *p; ++p) out[n++] = (uint16_t)*p;
    return n;
  }
  if (std::signbit(v) && !std::isnan(v)) out[n++] = '-';
  if (std::isinf(v)) {
    for (const char* p = "Infinity"; *p; ++p) out[n++] = (uint16_t)*p;
    return n;
  }
  double a = std::fabs(v);
  if (a == 0.0) {
    out[n++] = '0'; out[n++] = '.'; out[n++] = '0';
    return n;
  }
  char buf[48];
  const char* end;
#if defined(__cpp_lib_to_chars)
  {
    auto res = std::to_chars(buf, buf + sizeof(buf), a, std::chars_format::scientific);
    end = res.ptr;
  }
#else
  // GCC 10's libstdc++ ships no floating-point to_chars: find the shortest
  // round-trip digit count by probing snprintf %.*e at rising precision.
  // Correct rounding makes the first round-tripping precision produce the
  // same digits to_chars' shortest form would (the correctly-rounded
  // L-digit string is the closest one; if it doesn't round-trip, no other
  // L-digit string can).
  {
    int prec = 17;
    for (int p = 1; p <= 17; ++p) {
      std::snprintf(buf, sizeof(buf), "%.*e", p - 1, a);
      if (std::strtod(buf, nullptr) == a) {
        prec = p;
        break;
      }
    }
    (void)prec;
    end = buf + std::strlen(buf);
  }
#endif
  // parse "d[.ddd]e±xx" into digit string + decimal exponent
  char digits[24];
  int nd = 0;
  int exp10 = 0;
  {
    const char* p = buf;
    digits[nd++] = *p++;
    if (*p == '.' || *p == ',') {  // tolerate locale decimal separators
      ++p;
      while (p < end && *p != 'e' && *p != 'E') digits[nd++] = *p++;
    }
    // *p == 'e'
    ++p;
    bool neg = (*p == '-');
    if (*p == '+' || *p == '-') ++p;
    while (p < end && *p >= '0' && *p <= '9') exp10 = exp10 * 10 + (*p++ - '0');
    if (neg) exp10 = -exp10;
  }
  if (exp10 >= -3 && exp10 <= 6) {  // decimal form
    if (exp10 >= 0) {
      int i = 0;
      for (; i <= exp10; ++i) out[n++] = (uint16_t)(i < nd ? digits[i] : '0');
      out[n++] = '.';
      if (i >= nd) {
        out[n++] = '0';
      } else {
        for (; i < nd; ++i) out[n++] = (uint16_t)digits[i];
      }
    } else {
      out[n++] = '0'; out[n++] = '.';
      for (int z = 0; z < -exp10 - 1; ++z) out[n++] = '0';
      for (int i = 0; i < nd; ++i) out[n++] = (uint16_t)digits[i];
    }
  } else {  // scientific form d.dddE±x, no '+', no leading exponent zeros
    out[n++] = (uint16_t)digits[0];
    out[n++] = '.';
    if (nd == 1) {
      out[n++] = '0';
    } else {
      for (int i = 1; i < nd; ++i) out[n++] = (uint16_t)digits[i];
    }
    out[n++] = 'E';
    if (exp10 < 0) { out[n++] = '-'; exp10 = -exp10; }
    char eb[8];
    int ne = 0;
    while (exp10 > 0) { eb[ne++] = (char)('0' + exp10 % 10); exp10 /= 10; }
    while (ne > 0) out[n++] = (uint16_t)eb[--ne];
  }
  return n;
}

}  // namespace

extern "C" {

// Bucketed hash of "prefix" + Double.toString(vals[i]) for each row.
// `prefix` holds UTF-16 units (BMP column names; the caller checks).
void fh_hash_categorical_doubles(const double* vals, long n,
                                 const uint16_t* prefix, long prefix_len,
                                 int32_t num_features, int32_t* out) {
  uint16_t units[96];
  for (long j = 0; j < prefix_len; ++j) units[j] = prefix[j];
  for (long i = 0; i < n; ++i) {
    long len = prefix_len + render_java_double(vals[i], units + prefix_len);
    out[i] = bucket(murmur3_units(units, len), num_features);
  }
}

// Bucketed hash of "prefix" + row for a numpy '<U' column: `units32` is the
// raw UTF-32 buffer, `width` code points per row, NUL-padded. A row's length
// is last-nonzero+1 (embedded U+0000 are real characters; numpy cannot
// represent trailing ones). Astral code points are split into surrogate
// pairs, matching Java's UTF-16 storage.
void fh_hash_categorical_utf32(const uint32_t* units32, long n, long width,
                               const uint16_t* prefix, long prefix_len,
                               int32_t num_features, int32_t* out) {
  const long kMax = prefix_len + 2 * width + 4;
  uint16_t stack_units[256];
  uint16_t* units = kMax <= 256 ? stack_units : new uint16_t[kMax];
  for (long j = 0; j < prefix_len; ++j) units[j] = prefix[j];
  for (long i = 0; i < n; ++i) {
    const uint32_t* row = units32 + i * width;
    long wlen = width;
    while (wlen > 0 && row[wlen - 1] == 0) --wlen;
    long len = prefix_len;
    for (long j = 0; j < wlen; ++j) {
      uint32_t cp = row[j];
      if (cp > 0xFFFF) {
        cp -= 0x10000;
        units[len++] = (uint16_t)(0xD800 + (cp >> 10));
        units[len++] = (uint16_t)(0xDC00 + (cp & 0x3FF));
      } else {
        units[len++] = (uint16_t)cp;
      }
    }
    out[i] = bucket(murmur3_units(units, len), num_features);
  }
  if (units != stack_units) delete[] units;
}

// Merge each row's k (bucket, value) pairs into ascending-index padded CSR:
// equal buckets sum (TreeMap order of FeatureHasher.updateMap), -1 padding.
void fh_combine(const int32_t* idx, const double* val, long n, long k,
                int32_t* out_idx, double* out_val) {
  int32_t ib[64];
  double vb[64];
  for (long r = 0; r < n; ++r) {
    const int32_t* ri = idx + r * k;
    const double* rv = val + r * k;
    long m = 0;
    for (long j = 0; j < k; ++j) {  // insertion sort + duplicate merge
      int32_t key = ri[j];
      double value = rv[j];
      long lo = m;
      while (lo > 0 && ib[lo - 1] >= key) --lo;
      if (lo < m && ib[lo] == key) {
        vb[lo] += value;
        continue;
      }
      for (long s = m; s > lo; --s) { ib[s] = ib[s - 1]; vb[s] = vb[s - 1]; }
      ib[lo] = key;
      vb[lo] = value;
      ++m;
    }
    int32_t* oi = out_idx + r * k;
    double* ov = out_val + r * k;
    long j = 0;
    for (; j < m; ++j) { oi[j] = ib[j]; ov[j] = vb[j]; }
    for (; j < k; ++j) { oi[j] = -1; ov[j] = 0.0; }
  }
}

}  // extern "C"
